"""Paged attention: single-token decode over a paged KV cache.

The reference delegates LLM serving to vLLM via compiled DAGs
(SURVEY.md §2.2 P12 — "Ray's µs-latency GPU pipeline path"); the
TPU-native build owns the inference path instead (§7.10 "LLM inference
replica w/ paged attention"). KV blocks live in fixed-size pages
([num_pages, page_size, kv_heads, head_dim]); each sequence owns a list
of pages (its block table), so cache memory is allocated page-at-a-time
with zero fragmentation-driven copies — the vLLM idea, expressed as XLA
gathers instead of CUDA kernels:

  - decode: gather the sequence's pages with one `take` on the page axis
    (XLA lowers to a dynamic-gather DMA), then batched GQA attention on
    the MXU with masking past `context_lens`.
  - page writes are functional `.at[pages, offsets].set(...)` scatters,
    so the cache threads through jit with buffer donation.

Static shapes throughout: [B, max_pages] block tables padded with page 0
and masked by context_lens, so one compiled decode program serves every
batch composition (continuous batching never recompiles).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        return "cpu"


def _interpret_mode() -> bool:
    return os.environ.get("RAY_TPU_PALLAS_INTERPRET", "") == "1"


def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    sm_scale: float | None = None):
    """Decode-time attention for one new token per sequence.

    q:            [B, H, D]           query for the current position
    k_pages:      [P, page, KVH, D]   paged key cache (one layer)
    v_pages:      [P, page, KVH, D]   paged value cache
    block_tables: [B, max_pages] int32 page ids (padded entries ignored)
    context_lens: [B] int32           tokens in cache per sequence
                                      (including the current one)
    Returns [B, H, D].

    On TPU this runs the Pallas kernel below (pages stream through VMEM
    driven by the scalar-prefetched block table — the gathered
    [B, T, KVH, D] intermediate is never materialized in HBM); other
    platforms use the XLA gather formulation.
    """
    B, H, D = q.shape
    P, page, KVH, _ = k_pages.shape
    if ((_platform() == "tpu" or _interpret_mode())
            and D % 128 == 0 and H % KVH == 0):
        return _paged_attention_pallas(
            q, k_pages, v_pages, block_tables, context_lens,
            sm_scale if sm_scale is not None else 1.0 / math.sqrt(D))
    return _paged_attention_gather(
        q, k_pages, v_pages, block_tables, context_lens, sm_scale)


def _paged_attention_gather(q, k_pages, v_pages, block_tables,
                            context_lens, sm_scale: float | None = None):
    """XLA gather formulation (non-TPU fallback)."""
    B, H, D = q.shape
    P, page, KVH, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    G = H // KVH  # query heads per kv head (GQA)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    # Gather each sequence's pages: [B, max_pages, page, KVH, D] →
    # [B, T, KVH, D] with T = max_pages * page.
    k = jnp.take(k_pages, block_tables, axis=0).reshape(
        B, max_pages * page, KVH, D)
    v = jnp.take(v_pages, block_tables, axis=0).reshape(
        B, max_pages * page, KVH, D)

    qg = q.reshape(B, KVH, G, D)
    logits = jnp.einsum("bkgd,btkd->bkgt", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    t_idx = jnp.arange(max_pages * page, dtype=jnp.int32)
    valid = t_idx[None, :] < context_lens[:, None]           # [B, T]
    logits = jnp.where(valid[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgt,btkd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas decode kernel: one grid step per (sequence, page); the block
# table rides as a scalar-prefetch operand so each step's BlockSpec DMAs
# exactly the page it needs.  Flash-style running (max, sum, acc) in
# VMEM scratch across the page axis.
# ---------------------------------------------------------------------------


def _paged_decode_kernel(tables_ref, ctx_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, page: int, W: int,
                         kvh: int, g: int, sm_scale: float):
    b = pl.program_id(0)
    w = pl.program_id(1)

    @pl.when(w == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[b]

    @pl.when(w * page < ctx)
    def _compute():
        d = q_ref.shape[-1]
        q = q_ref[0].astype(jnp.float32).reshape(kvh, g, d)   # [KVH,G,D]
        k = k_ref[0].astype(jnp.float32)                      # [page,KVH,D]
        v = v_ref[0].astype(jnp.float32)
        kt = k.transpose(1, 0, 2)                             # [KVH,page,D]
        logits = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale    # [KVH,G,page]
        pos = w * page + jax.lax.broadcasted_iota(
            jnp.int32, (kvh, g, page), 2)
        logits = jnp.where(pos < ctx, logits, -jnp.inf)

        m_prev = m_ref[...]                                   # [KVH, G]
        m_new = jnp.maximum(m_prev, logits.max(axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new[..., None])                # [KVH,G,page]
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
        vt = v.transpose(1, 0, 2)                             # [KVH,page,D]
        pv = jax.lax.dot_general(
            p, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)               # [KVH,G,D]
        acc_ref[...] = acc_ref[...] * alpha[..., None] + pv
        m_ref[...] = m_new

    @pl.when(w == W - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)[..., None]
        h = kvh * g
        o_ref[0] = (acc_ref[...] / l).reshape(h, q_ref.shape[-1]) \
            .astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, block_tables,
                            context_lens, sm_scale: float):
    B, H, D = q.shape
    P, page, KVH, _ = k_pages.shape
    W = block_tables.shape[1]
    G = H // KVH

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, W),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, w, tables, ctx: (b, 0, 0)),
            pl.BlockSpec((1, page, KVH, D),
                         lambda b, w, tables, ctx: (tables[b, w], 0, 0, 0)),
            pl.BlockSpec((1, page, KVH, D),
                         lambda b, w, tables, ctx: (tables[b, w], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, H, D), lambda b, w, tables, ctx: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KVH, G), jnp.float32),
            pltpu.VMEM((KVH, G), jnp.float32),
            pltpu.VMEM((KVH, G, D), jnp.float32),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page=page, W=W, kvh=KVH,
                          g=G, sm_scale=sm_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=_interpret_mode(),
    )
    return kernel(block_tables.astype(jnp.int32),
                  context_lens.astype(jnp.int32), q, k_pages, v_pages)


def write_page_tokens(k_pages, v_pages, k_new, v_new, block_tables,
                      positions):
    """Scatter new K/V rows into their pages.

    k_new/v_new: [B, S, KVH, D] projections for S new tokens per seq;
    positions:   [B, S] int32 absolute positions (define page + offset);
    block_tables:[B, max_pages].
    Returns updated (k_pages, v_pages). Rows with position < 0 are
    dropped (write to a scratch page slot) so padded prefills are safe.
    """
    B, S, KVH, D = k_new.shape
    page = k_pages.shape[1]
    page_idx = positions // page                              # [B, S]
    offset = positions % page
    valid = positions >= 0
    pages = jnp.take_along_axis(
        block_tables, jnp.maximum(page_idx, 0), axis=1)       # [B, S]
    # Invalid rows get page index == num_pages: past-the-end is
    # out-of-bounds under scatter mode="drop" (negative indices would
    # WRAP, silently corrupting the last page), so those writes vanish.
    pages = jnp.where(valid, pages, k_pages.shape[0])
    flat_pages = pages.reshape(-1)
    flat_off = jnp.maximum(offset, 0).reshape(-1)
    k_flat = k_new.reshape(-1, KVH, D)
    v_flat = v_new.reshape(-1, KVH, D)
    k_pages = k_pages.at[flat_pages, flat_off].set(
        k_flat, mode="drop")
    v_pages = v_pages.at[flat_pages, flat_off].set(
        v_flat, mode="drop")
    return k_pages, v_pages


def paged_attention_reference(q, k_pages, v_pages, block_tables,
                              context_lens):
    """O(B·T) numpy-style reference for tests: per-sequence dense
    attention over the gathered cache."""
    import numpy as np

    q = np.asarray(q, dtype=np.float64)
    k_pages = np.asarray(k_pages, dtype=np.float64)
    v_pages = np.asarray(v_pages, dtype=np.float64)
    block_tables = np.asarray(block_tables)
    context_lens = np.asarray(context_lens)
    B, H, D = q.shape
    page = k_pages.shape[1]
    KVH = k_pages.shape[2]
    G = H // KVH
    out = np.zeros_like(q)
    for b in range(B):
        n = int(context_lens[b])
        if n == 0:
            continue
        ks, vs = [], []
        for t in range(n):
            p = block_tables[b, t // page]
            ks.append(k_pages[p, t % page])
            vs.append(v_pages[p, t % page])
        k = np.stack(ks)  # [n, KVH, D]
        v = np.stack(vs)
        for h in range(H):
            kh = h // G
            logits = (k[:, kh] @ q[b, h]) / np.sqrt(D)
            w = np.exp(logits - logits.max())
            w = w / w.sum()
            out[b, h] = w @ v[:, kh]
    return out
