"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Greenfield capability (SURVEY.md §5 — the reference has no sequence/context
parallelism in-tree; §2.4 mandates it as a first-class mesh axis).  Design
follows Liu et al.'s ring attention (PAPERS.md): each device holds a query
chunk and a rotating key/value chunk; K/V travel around the ring via
`jax.lax.ppermute` while online-softmax statistics (out, logsumexp)
accumulate — the full s×s score matrix never exists, and the per-step
block compute overlaps the ICI transfer (XLA pipelines ppermute with the
einsums).

Two entry points:
  - `ring_attention_sharded(q, k, v, axis_name, causal)`: collective form,
    call inside shard_map/pmap with a named sequence axis.
  - `ring_attention(q, k, v, mesh, causal)`: jit-level wrapper that
    shard_maps over the mesh's "seq" axis (data/tensor axes stay sharded,
    everything else replicated).

Layout: q, k, v are [batch, seq_local, heads, head_dim] (models/
convention, GQA pre-expanded by the caller).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = float(-1e30)


def _chunk_attention(q, k, v, mask, sm_scale) -> Tuple[jax.Array, jax.Array]:
    """Attention of q against one K/V chunk.

    Returns (out, lse): out [b,sq,h,hd] normalized within the chunk,
    lse [b,h,sq] the chunk's logsumexp — the merge statistics of
    flash/blockwise attention.
    """
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits * sm_scale
    if mask is not None:
        logits = jnp.where(mask, logits, _NEG_INF)
    m = jnp.max(logits, axis=-1)                      # [b,h,q]
    # fully-masked rows: keep exp() finite, lse = -inf marks "no weight"
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    denom = jnp.sum(p, axis=-1)                       # [b,h,q]
    lse = jnp.where(
        denom > 0, m_safe + jnp.log(jnp.maximum(denom, 1e-30)), _NEG_INF)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    out = out / jnp.maximum(denom, 1e-30).transpose(0, 2, 1)[..., None]
    return out, lse


def _merge(o1, l1, o2, l2):
    """Merge two partial attention results by their logsumexps."""
    l_max = jnp.maximum(l1, l2)
    l_max_safe = jnp.where(l_max <= _NEG_INF / 2, 0.0, l_max)
    w1 = jnp.exp(l1 - l_max_safe)
    w2 = jnp.exp(l2 - l_max_safe)
    denom = jnp.maximum(w1 + w2, 1e-30)
    # broadcast [b,h,q] weights onto [b,q,h,d]
    def bc(w):
        return w.transpose(0, 2, 1)[..., None]

    out = (o1 * bc(w1) + o2 * bc(w2)) / bc(denom)
    lse = jnp.where(
        jnp.maximum(l1, l2) <= _NEG_INF / 2,
        _NEG_INF,
        l_max_safe + jnp.log(denom))
    return out, lse


def ring_attention_sharded(q, k, v, axis_name: str, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           block_q: int = 128, block_k: int = 128):
    """Collective ring attention; call inside shard_map over ``axis_name``.

    q, k, v: [b, s_local, h, hd] — this device's sequence chunk.

    The per-step chunk op is the offset-aware Pallas flash kernel
    (ops/attention.py flash_attention_chunk) whenever shapes allow: the
    s_local×s_local score block then never materializes in HBM, in
    forward OR backward (the kernel's custom VJP recomputes by block
    from the saved lse).  Global positions enter the kernel as dynamic
    scalars, so one compiled program serves every ring step.
    """
    from ray_tpu.ops.attention import _can_use_pallas, flash_attention_chunk

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, hd = q.shape

    bq, bk = min(block_q, s_loc), min(block_k, s_loc)
    use_flash = _can_use_pallas(s_loc, s_loc, hd, bq, bk)
    q_pos = my * s_loc + jnp.arange(s_loc)            # global q positions

    o = jnp.zeros((b, s_loc, h, hd), jnp.float32)
    lse = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)

    # perm: chunk travels to the next device each step (ring)
    perm = [(i, (i + 1) % n) for i in range(n)]

    k_cur, v_cur = k, v
    for step in range(n):
        # after `step` rotations this device holds the chunk that started
        # on device (my - step) mod n
        src = (my - step) % n
        if use_flash:
            o_c, lse_flat = flash_attention_chunk(
                q, k_cur, v_cur, my * s_loc, src * s_loc,
                causal=causal, sm_scale=sm_scale, block_q=bq, block_k=bk)
            o_c = o_c.astype(jnp.float32)
            lse_c = lse_flat.reshape(b, h, s_loc)
        else:
            kv_pos = src * s_loc + jnp.arange(s_loc)
            if causal:
                mask = q_pos[:, None] >= kv_pos[None, :]  # global causal
                mask = mask[None, None, :, :]             # [1,1,sq,sk]
            else:
                mask = None
            o_c, lse_c = _chunk_attention(q, k_cur, v_cur, mask, sm_scale)
        o, lse = _merge(o, lse, o_c, lse_c)
        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    return o.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, causal: bool = True,
                   seq_axis: str = "seq",
                   batch_axes: Tuple[str, ...] = ("data", "fsdp"),
                   heads_axis: str = "tensor"):
    """jit-level ring attention: shard_maps over the mesh's sequence axis.

    q, k, v: [b, s, h, hd] global arrays (GQA pre-expanded).  Batch stays
    sharded over ``batch_axes``, heads over ``heads_axis``; the sequence
    axis rotates K/V chunks around the ring.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or mesh.empty:
            raise ValueError("ring_attention requires a mesh "
                             "(pass mesh= or trace under `with mesh:`)")
    axis_names = set(mesh.axis_names)
    batch = tuple(a for a in batch_axes if a in axis_names)
    heads = heads_axis if heads_axis in axis_names else None
    spec = P(batch if batch else None, seq_axis, heads, None)

    fn = functools.partial(
        ring_attention_sharded, axis_name=seq_axis, causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)(q, k, v)
