"""Per-worker train session: report / get_checkpoint / get_dataset_shard.

Counterpart of the reference's train/_internal/session.py `_TrainSession`
(:110 — report() :402 queues results to the driver, get_dataset_shard :477)
and the module-level `ray.train.report/get_context` API.  The user training
loop runs in a daemon thread inside the train-worker actor; `report()` hands
(metrics, checkpoint) to the actor's result queue with maxsize-1
backpressure, exactly the reference's result-queue flow (trainer.py:31
TrainingIterator pulls).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, Iterable, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


@dataclasses.dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    experiment_name: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _TrainSession:
    def __init__(self, context: TrainContext,
                 checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.context = context
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        # maxsize=1: the loop blocks in report() until the driver consumed
        # the previous result — keeps driver and workers in lockstep.
        self.result_queue: "queue.Queue" = queue.Queue(maxsize=1)
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self._last_report_t: Optional[float] = None

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self._note_device_step(metrics)
        self.result_queue.put({"metrics": dict(metrics),
                               "checkpoint": checkpoint})

    def _note_device_step(self, metrics: Dict[str, Any]) -> None:
        """Device-plane step hook (same accounting the serve engine's
        step sampler does): when the loop reports modeled per-step
        work — "step_flops" and/or "step_bytes", or a ready-made
        "tokens_per_sec" with "flops_per_token" — fold it into the
        continuous roofline/MFU gauges tagged plane="train".  Loops
        that report neither pay one dict lookup."""
        now = time.time()
        prev, self._last_report_t = self._last_report_t, now
        flops = metrics.get("step_flops")
        nbytes = metrics.get("step_bytes")
        tok_s = metrics.get("tokens_per_sec")
        if flops is None and nbytes is None and tok_s is None:
            return
        try:
            from ray_tpu.util import device_stats, tracing

            if tok_s is not None:
                frac, mfu = device_stats.note_step(
                    tokens_per_s=float(tok_s),
                    bytes_per_token=float(
                        metrics.get("bytes_per_token", 0.0)),
                    flops_per_token=float(
                        metrics.get("flops_per_token", 0.0)),
                    plane="train")
            elif prev is not None and now > prev:
                # One report == one step: per-"token" terms collapse to
                # per-step terms at 1/dt steps per second.
                frac, mfu = device_stats.note_step(
                    tokens_per_s=1.0 / (now - prev),
                    bytes_per_token=float(nbytes or 0.0),
                    flops_per_token=float(flops or 0.0),
                    plane="train")
            else:
                return
            if prev is not None and now > prev:
                tracing.record_span(
                    "device.step", prev, now,
                    attributes={"plane": "train",
                                "roofline_fraction": round(frac, 5),
                                "mfu": round(mfu, 5)})
        except Exception:  # raylint: allow-swallow(telemetry must never fail a train step report)
            pass


def _set_session(s: Optional[_TrainSession]):
    global _session
    with _session_lock:
        _session = s


def _get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session active: ray_tpu.train.report()/get_context() "
            "may only be called inside a training loop run by a Trainer.")
    return _session


# -- public module-level API (ray.train.* parity) ---------------------------

def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    _get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _get_session().loaded_checkpoint


def get_context() -> TrainContext:
    return _get_session().context


def get_dataset_shard(name: str = "train"):
    shard = _get_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset shard {name!r}; pass datasets={{{name!r}: ds}} to "
            f"the Trainer")
    return shard
