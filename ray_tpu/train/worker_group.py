"""Training worker group: N actors in a placement group.

Counterpart of the reference's train/_internal/worker_group.py (`WorkerGroup`
:102 — plain Ray actors; execute/execute_async :260/:233) plus the worker-side
half of backend_executor.start_training (:441): each worker hosts a
`_TrainSession` and runs the user loop in a daemon thread, surfacing results
through a polled queue.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint, StorageContext
from ray_tpu.train import session as _session_mod
from ray_tpu.train.session import TrainContext, _TrainSession


class TrainWorker:
    """Actor hosting one training process (rank)."""

    def __init__(self, rank: int, world_size: int, run_dir: str,
                 env: Optional[Dict[str, str]] = None,
                 num_to_keep: Optional[int] = None):
        self.rank = rank
        self.world_size = world_size
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.session: Optional[_TrainSession] = None
        self.thread: Optional[threading.Thread] = None
        for k, v in (env or {}).items():
            if k == "XLA_FLAGS" and os.environ.get(k):
                # Append, replacing any existing setting of the same flag
                # (a substring test would skip e.g. count=1 when count=12
                # is already present).
                flag_name = v.split("=", 1)[0]
                kept = [f for f in os.environ[k].split()
                        if f.split("=", 1)[0] != flag_name]
                os.environ[k] = " ".join(kept + [v])
            else:
                os.environ[k] = v

    # -- generic execution (WorkerGroup.execute parity) ---------------------
    def run(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def node_info(self) -> Dict[str, Any]:
        return {
            "rank": self.rank,
            "pid": os.getpid(),
            "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
        }

    # -- training lifecycle -------------------------------------------------
    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       checkpoint_path: Optional[str],
                       dataset_shards: Optional[Dict[str, Any]],
                       experiment_name: str) -> bool:
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        ctx = TrainContext(
            world_size=self.world_size, world_rank=self.rank,
            local_rank=self.rank, node_rank=self.rank,
            experiment_name=experiment_name)
        self.session = _TrainSession(ctx, ckpt, dataset_shards)
        _session_mod._set_session(self.session)
        storage = StorageContext(
            os.path.dirname(self.run_dir), os.path.basename(self.run_dir),
            num_to_keep=self.num_to_keep)

        def runner():
            s = self.session
            try:
                if _takes_config(train_fn):
                    train_fn(config)
                else:
                    train_fn()
            except BaseException as e:  # noqa: BLE001 — surfaced to driver
                s.error = e
                s.result_queue.put({
                    "error": f"{type(e).__name__}: {e}",
                    "traceback": traceback.format_exc(),
                })
            finally:
                s.finished.set()

        # Persist checkpoints worker-side (rank 0), reference
        # storage.py:508 persist_current_checkpoint runs on the worker.
        orig_report = self.session.report

        def reporting(metrics, checkpoint=None):
            if checkpoint is not None and self.rank == 0:
                persisted = storage.persist_checkpoint(
                    checkpoint.as_directory(), metrics)
                checkpoint = persisted
            orig_report(metrics, checkpoint)

        self.session.report = reporting
        self.thread = threading.Thread(target=runner, daemon=True)
        self.thread.start()
        return True

    def next_result(self, timeout: float = 1.0) -> Optional[Dict[str, Any]]:
        """One queued result, {'finished': True} at end, None if no news."""
        import queue as _q

        s = self.session
        if s is None:
            return None
        try:
            item = s.result_queue.get(timeout=timeout)
        except _q.Empty:
            if s.finished.is_set() and s.result_queue.empty():
                return {"finished": True}
            return None
        if item.get("checkpoint") is not None:
            item["checkpoint_path"] = item.pop("checkpoint").as_directory()
        return item

    def shutdown(self) -> bool:
        return True


def _takes_config(fn: Callable) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return True
    positional = [p for p in sig.parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                                p.VAR_POSITIONAL)]
    # Keyword-only / **kwargs-only loops take no config positionally.
    return len(positional) >= 1


class WorkerGroup:
    """N TrainWorker actors, optionally inside a placement group."""

    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 run_dir: str, placement_strategy: str = "PACK",
                 env: Optional[Dict[str, str]] = None,
                 num_to_keep: Optional[int] = None):
        from ray_tpu.util.placement_group import (
            placement_group,
            remove_placement_group,
        )
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        self.num_workers = num_workers
        self._pg = placement_group(
            [dict(resources_per_worker) for _ in range(num_workers)],
            strategy=placement_strategy)
        if not self._pg.wait(timeout_seconds=60):
            remove_placement_group(self._pg)
            raise RuntimeError(
                f"placement group for {num_workers} train workers "
                f"({resources_per_worker}/worker) not schedulable")
        cls = ray_tpu.remote(TrainWorker)
        self.workers: List = [
            cls.options(
                resources=dict(resources_per_worker),
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg, placement_group_bundle_index=i),
            ).remote(i, num_workers, run_dir, env, num_to_keep)
            for i in range(num_workers)
        ]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [w.run.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=300)

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[rank].run.remote(fn, *args, **kwargs), timeout=300)

    def shutdown(self):
        from ray_tpu.util.placement_group import remove_placement_group

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass
        self.workers = []
