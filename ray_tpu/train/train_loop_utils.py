"""In-loop helpers: the JAX equivalents of ray.train.torch.prepare_model /
prepare_data_loader (reference train/torch/train_loop_utils.py:158/:200).

On torch, "prepare" wraps the model in DDP and the loader in a distributed
sampler.  On TPU/JAX, "prepare" means: build the global mesh once, device_put
params with their GSPMD shardings, and shard each host batch onto the data
axes — after which the jitted step needs no further distribution code.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import build_mesh
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    Rules,
    data_sharding,
    shard_tree,
)


def get_mesh(axes: Optional[Dict[str, int]] = None,
             dcn_axes=()) -> Any:
    """Global mesh over every device in the (possibly multi-process) runtime.

    Call after the JaxBackend ran jax.distributed.initialize: jax.devices()
    is then the global device set, so the same mesh (and the same jitted
    program) spans all train workers.
    """
    return build_mesh(axes=axes or {}, dcn_axes=dcn_axes)


def prepare_pytree(params: Any, mesh=None, rules: Rules = DEFAULT_RULES,
                   logical_axes: Any = None) -> Any:
    """Shard a parameter pytree onto the mesh (prepare_model equivalent)."""
    mesh = mesh if mesh is not None else get_mesh()
    return shard_tree(params, mesh, rules, logical_axes)


def shard_batch(batch: Any, mesh=None) -> Any:
    """Place a host batch with its leading dim over the data axes
    (prepare_data_loader equivalent — per-batch, iterator-agnostic)."""
    import jax

    mesh = mesh if mesh is not None else get_mesh()
    return jax.device_put(batch, data_sharding(mesh))
