"""Train/AIR config dataclasses.

Counterparts of the reference's python/ray/air/config.py (ScalingConfig,
RunConfig, FailureConfig, CheckpointConfig) with TPU-first fields: workers
request TPU chips instead of GPUs, and mesh axes are declared here so the
backend can build one global `jax.sharding.Mesh` across the worker group.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many training workers and what each needs.

    num_workers: host-level workers (actors). On TPU one worker per host,
    each driving its local chips through one jax runtime (the reference's
    worker==GPU-process model becomes worker==host, SURVEY.md §7 step 5).
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    tpu_chips_per_worker: int = 1  # chips reserved per worker
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if "CPU" not in res:
            res["CPU"] = 1.0
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = float(self.tpu_chips_per_worker)
        return res


@dataclasses.dataclass
class FailureConfig:
    """max_failures: worker-group restarts before giving up (reference
    FailureConfig air/config.py; restart logic backend_executor._restart)."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0  # library-driven ckpt every N reports (0=user)


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # default: /tmp/ray_tpu_results
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    # Tune stop criterion: {"metric": threshold} (stop when >=) or a
    # callable (trial_id, metrics) -> bool (reference air.RunConfig.stop).
    stop: Optional[object] = None
    # Experiment-loop callbacks (tune/callbacks.py Callback; reference
    # air.RunConfig.callbacks).  JSON/CSV loggers are added by default.
    callbacks: Optional[list] = None
