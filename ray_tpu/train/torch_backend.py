"""Torch backend: torch.distributed process groups over the worker group.

Counterpart of the reference's Train Torch backend
(python/ray/train/torch/config.py:150 — TCP-store rendezvous from the
rank-0 address :65, `dist.init_process_group`) and the worker loop
utilities (torch/train_loop_utils.py:158 prepare_model / :200
prepare_data_loader). The compute story differs from the reference's
flagship — on this stack JAX/XLA owns the accelerators — but torch-CPU
data-parallel training is a real workload (and the image bakes torch),
so the backend does real gloo DDP, not a stub.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ray_tpu.train.backend import Backend, BackendConfig, _free_port


@dataclass
class TorchConfig(BackendConfig):
    """backend: torch.distributed backend name ("gloo" on CPU hosts);
    init_timeout_s: process-group rendezvous timeout."""

    backend: str = "gloo"
    init_timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return TorchBackend


def _setup_torch_process_group(master_addr: str, master_port: int,
                               rank: int, world_size: int, backend: str,
                               timeout_s: float) -> Dict[str, int]:
    """Runs ON each train worker (reference torch/config.py:65)."""
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    dist.init_process_group(
        backend=backend,
        init_method=f"tcp://{master_addr}:{master_port}",
        rank=rank, world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s))
    return {"rank": dist.get_rank(), "world_size": dist.get_world_size()}


def _shutdown_torch_process_group() -> bool:
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig):
        import ray_tpu

        n = worker_group.num_workers
        if n <= 1:
            return  # single worker: no process group needed
        port = _free_port()
        refs = [
            w.run.remote(
                _setup_torch_process_group, "127.0.0.1", port, i, n,
                backend_config.backend, backend_config.init_timeout_s)
            for i, w in enumerate(worker_group.workers)
        ]
        infos = ray_tpu.get(refs,
                            timeout=backend_config.init_timeout_s + 30)
        for info in infos:
            if info["world_size"] != n:
                raise RuntimeError(
                    f"torch process group world size mismatch: {infos}")

    def on_shutdown(self, worker_group, backend_config: TorchConfig):
        import ray_tpu

        try:
            ray_tpu.get(
                [w.run.remote(_shutdown_torch_process_group)
                 for w in worker_group.workers], timeout=30)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Worker-loop utilities (reference torch/train_loop_utils.py)
# ---------------------------------------------------------------------------

def prepare_model(model):
    """Wrap the model in DDP when a process group is active
    (reference prepare_model :158, minus the GPU device moves)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


class _EpochedLoader:
    """DataLoader wrapper that advances the DistributedSampler epoch on
    every full iteration (the reference's _WrappedDataLoader role) so
    shuffling reshuffles per epoch instead of repeating one permutation."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


def prepare_data_loader(data_loader):
    """Re-create the loader with a DistributedSampler so each rank sees
    its shard (reference prepare_data_loader :200). Preserves the
    loader's shuffle setting and reshuffles per epoch."""
    import torch.distributed as dist
    from torch.utils.data import (
        DataLoader,
        DistributedSampler,
        RandomSampler,
    )

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    shuffled = isinstance(data_loader.sampler, RandomSampler)
    sampler = DistributedSampler(data_loader.dataset, shuffle=shuffled)
    loader = DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last)
    return _EpochedLoader(loader, sampler)
