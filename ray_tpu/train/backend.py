"""Training backends: per-worker runtime setup before the user loop runs.

Counterpart of the reference's train/backend.py `Backend` ABC (:32,
on_start/on_training_start/on_shutdown) and train/torch/config.py
(`_setup_torch_process_group` :65 — TCP-store rendezvous + NCCL).  The
TPU-native backend swaps the NCCL process group for
`jax.distributed.initialize`: after it, every worker sees the GLOBAL device
set and one jitted program spans the whole mesh — no per-collective process
groups exist to manage (SURVEY.md §3.4 swap point).
"""

from __future__ import annotations

import dataclasses
import socket
from typing import Dict, Optional


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_training_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        pass


@dataclasses.dataclass
class JaxBackendConfig(BackendConfig):
    """distributed_init: run jax.distributed.initialize across workers so
    they form one multi-process JAX runtime (None = auto: only when
    num_workers > 1).  host_device_count: force N virtual CPU devices per
    worker (test mode — SURVEY.md §4 blueprint); platform: override
    JAX_PLATFORMS in workers."""

    distributed_init: Optional[bool] = None
    coordinator_port: int = 0
    platform: Optional[str] = None
    host_device_count: Optional[int] = None

    @property
    def backend_cls(self):
        return JaxBackend


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _jax_env(config: JaxBackendConfig) -> Dict[str, str]:
    env: Dict[str, str] = {}
    if config.platform:
        env["JAX_PLATFORMS"] = config.platform
    if config.host_device_count:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count="
            f"{config.host_device_count}")
    return env


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int, platform: Optional[str]):
    """Runs ON the train worker (before any other jax use there).

    Env vars (JAX_PLATFORMS / XLA_FLAGS) were already applied by
    TrainWorker.__init__ from _jax_env — the single authoritative path;
    only the jax.config override is needed here because a sitecustomize
    that imported jax first would ignore the env var."""
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id)
    return {"process_id": jax.process_index(),
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices())}


def _shutdown_jax_distributed():
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    return True


class JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxBackendConfig):
        import ray_tpu

        n = worker_group.num_workers
        do_dist = backend_config.distributed_init
        if do_dist is None:
            do_dist = n > 1
        if not do_dist:
            return
        port = backend_config.coordinator_port or _free_port()
        coordinator = f"127.0.0.1:{port}"
        # TODO multi-node: use rank-0 worker's node IP from node_info().
        refs = [
            w.run.remote(
                _init_jax_distributed, coordinator, n, i,
                backend_config.platform)
            for i, w in enumerate(worker_group.workers)
        ]
        infos = ray_tpu.get(refs, timeout=120)
        total = infos[0]["global_devices"]
        for info in infos:
            if info["global_devices"] != total:
                raise RuntimeError(
                    "workers disagree on the global device count after "
                    f"jax.distributed init: {infos}")

    def on_shutdown(self, worker_group, backend_config: JaxBackendConfig):
        import ray_tpu

        try:
            ray_tpu.get(
                [w.run.remote(_shutdown_jax_distributed)
                 for w in worker_group.workers], timeout=30)
        except Exception:
            pass
