"""Sharded train state + jitted training step for the flagship transformer.

The reference's per-strategy process-group setup (train/torch/config.py:65
`_setup_torch_process_group`, DDP wrap in train_loop_utils.py:158) collapses on
TPU into ONE jitted function over a named mesh: GSPMD inserts the gradient
psum on the `data`/`fsdp` axes, parameter all-gathers for FSDP, and tensor
collectives for TP.  This module owns that step; trainers (train/),
learners (rl/) and the bench harness all reuse it.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import transformer as tfm
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    Rules,
    data_sharding,
    tree_shardings,
)


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10000,
                      b1: float = 0.9, b2: float = 0.95,
                      grad_clip: float = 1.0,
                      mu_dtype=None,
                      nu_dtype=None) -> optax.GradientTransformation:
    """AdamW + cosine schedule + global-norm clip — the Llama recipe.

    mu_dtype/nu_dtype=jnp.bfloat16 halve the moment state (down to
    8 B/param with both) — the trade that buys billion-class models
    (and faster remat policies) room in a single chip's HBM."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    if nu_dtype is not None:
        from ray_tpu.train.optim import adamw as lean_adamw

        return optax.chain(
            optax.clip_by_global_norm(grad_clip),
            lean_adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay,
                       mu_dtype=mu_dtype, nu_dtype=nu_dtype),
        )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def _constrain_like_params(tree: Any, params_treedef, param_shardings):
    """Apply param shardings to every params-shaped sub-pytree (optax mu/nu).

    Optimizer state is a nest of (named)tuples whose momentum terms mirror the
    param tree; walking the nest and constraining matching subtrees keeps the
    optimizer sharded FSDP-style with zero per-optimizer knowledge.
    """

    def rec(x):
        try:
            if jax.tree.structure(x) == params_treedef:
                return jax.tree.map(
                    jax.lax.with_sharding_constraint, x, param_shardings)
        except Exception:
            pass
        if hasattr(x, "_fields"):  # NamedTuple
            return type(x)(*[rec(v) for v in x])
        if isinstance(x, tuple):
            return tuple(rec(v) for v in x)
        if isinstance(x, list):
            return [rec(v) for v in x]
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        return x

    return rec(tree)


class ShardedTrainStep:
    """Factory for sharded init/step functions on a mesh.

    Usage:
        ts = ShardedTrainStep(config, mesh)
        state = ts.init(jax.random.key(0))
        state, metrics = ts.step(state, batch)   # batch: {"tokens": [b, s+1]}
    """

    def __init__(self, config: tfm.TransformerConfig, mesh,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 rules: Rules = DEFAULT_RULES,
                 loss_fn: Optional[Callable] = None,
                 num_microbatches: Optional[int] = None):
        self.config = config
        self.mesh = mesh
        self.optimizer = optimizer or default_optimizer()
        self.rules = rules
        # Pipeline parallelism: a stage axis >1 in the mesh routes the
        # loss through the GPipe-pipelined forward (greenfield vs the
        # reference — Ray ships no in-tree PP, SURVEY.md §2.4).  Params
        # keep their [L, ...] layout; the layers->stage rule shards the
        # layer dim so each device already holds its stage's run.
        self.num_stages = int(dict(mesh.shape).get("stage", 1))
        self.num_microbatches = num_microbatches
        if loss_fn is not None:
            self.loss_fn = loss_fn
        elif self.num_stages > 1:
            self.loss_fn = lambda p, b: tfm.loss_fn_pipelined(
                p, b, config, self.num_stages, self.num_microbatches,
                mesh=mesh)
        else:
            self.loss_fn = lambda p, b: tfm.loss_fn(p, b, config)
        self.param_logical = tfm.logical_axes(config)
        self.param_shardings = tree_shardings(
            mesh, self.param_logical, rules)
        self.batch_sharding = data_sharding(mesh)
        self._params_treedef = jax.tree.structure(self.param_logical)

        self._init = jax.jit(self._init_fn)
        self._step = jax.jit(self._step_fn, donate_argnums=(0,))

    # -- init ---------------------------------------------------------------
    def _init_fn(self, rng):
        params = tfm.init_params(self.config, rng)
        params = jax.tree.map(
            jax.lax.with_sharding_constraint, params, self.param_shardings)
        opt_state = self.optimizer.init(params)
        opt_state = _constrain_like_params(
            opt_state, self._params_treedef, self.param_shardings)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    def init(self, rng):
        with self.mesh:
            return self._init(rng)

    # -- step ---------------------------------------------------------------
    def _step_fn(self, state, batch):
        def loss(p):
            return self.loss_fn(p, batch)

        loss_val, grads = jax.value_and_grad(loss)(state["params"])
        grads = jax.tree.map(
            jax.lax.with_sharding_constraint, grads, self.param_shardings)
        updates, opt_state = self.optimizer.update(
            grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        params = jax.tree.map(
            jax.lax.with_sharding_constraint, params, self.param_shardings)
        metrics = {
            "loss": loss_val.astype(jnp.float32),
            "grad_norm": optax.global_norm(grads).astype(jnp.float32),
            "step": state["step"] + 1,
        }
        return {"params": params, "opt_state": opt_state,
                "step": state["step"] + 1}, metrics

    def step(self, state, batch):
        batch = jax.device_put(batch, self.batch_sharding)
        with self.mesh:
            return self._step(state, batch)

    # -- eval ----------------------------------------------------------------
    @functools.cached_property
    def _eval(self):
        def eval_fn(params, batch):
            return self.loss_fn(params, batch).astype(jnp.float32)

        return jax.jit(eval_fn)

    def eval_step(self, params, batch):
        batch = jax.device_put(batch, self.batch_sharding)
        with self.mesh:
            return self._eval(params, batch)
