"""DataParallelTrainer / JaxTrainer: the Train-library driver.

Counterpart of the reference's train/data_parallel_trainer.py (:25,
training_loop :428) + train/_internal/backend_executor.py (:67; start :129
creates PG + WorkerGroup, start_training :441 wires sessions,
get_with_failure_handling :675 and _restart :736 for fault tolerance) +
train/trainer.py TrainingIterator (:31).  Collapsed into one driver class:
our worker group already runs sessions worker-side.

JaxTrainer is to this what the reference's TorchTrainer is to
DataParallelTrainer — the JAX backend is the default.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.backend import BackendConfig, JaxBackendConfig
from ray_tpu.train.checkpoint import Checkpoint, StorageContext
from ray_tpu.train.config import RunConfig, ScalingConfig


class TrainingFailedError(RuntimeError):
    """Training did not complete (worker failures exceeded max_failures, or
    the training loop raised)."""


@dataclasses.dataclass
class Result:
    """Counterpart of python/ray/air/result.py Result."""

    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    @property
    def best_checkpoints(self):
        return [self.checkpoint] if self.checkpoint else []


def _shard_dataset(ds: Any, num_shards: int) -> List[Any]:
    """Split one dataset into per-worker shards.

    ray_tpu.data Datasets use streaming_split (locality-aware iterators,
    reference dataset.py:1236); plain sequences/arrays are sliced; anything
    else is replicated.
    """
    if hasattr(ds, "streaming_split"):
        return ds.streaming_split(num_shards)
    try:
        n = len(ds)
    except TypeError:
        return [ds] * num_shards
    per = (n + num_shards - 1) // num_shards
    return [ds[i * per:(i + 1) * per] for i in range(num_shards)]


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.backend_config = backend_config or BackendConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cfg = self.run_config
        storage = StorageContext(
            cfg.storage_path, cfg.name,
            num_to_keep=cfg.checkpoint_config.num_to_keep)
        max_failures = cfg.failure_config.max_failures
        failures = 0
        latest_ckpt = self.resume_from_checkpoint
        history: List[Dict[str, Any]] = []

        # RunConfig.callbacks reach standalone fits too (reference:
        # Train dispatches the same tune Callback surface; SURVEY L6
        # AIR-shared config).  The run is exposed to callbacks as one
        # trial-shaped handle.
        from ray_tpu.tune.callbacks import default_callbacks

        callbacks = default_callbacks(getattr(cfg, "callbacks", None))
        handle = _RunHandle(
            trial_id=storage.name or "train_run",
            trial_dir=storage.run_dir,
            config=dict(self.train_loop_config),
            metrics_history=history)
        callbacks.setup(run_dir=storage.run_dir, trials=[handle])
        callbacks.on_trial_start(trial=handle)
        try:
            while True:
                try:
                    metrics = self._run_attempt(
                        storage, latest_ckpt, history,
                        callbacks=callbacks, handle=handle)
                    callbacks.on_trial_complete(trial=handle)
                    return Result(
                        metrics=metrics,
                        checkpoint=storage.latest_checkpoint(),
                        path=storage.run_dir,
                        metrics_history=history)
                except TrainingFailedError:
                    callbacks.on_trial_error(trial=handle)
                    raise
                except Exception as e:
                    failures += 1
                    if max_failures >= 0 and failures > max_failures:
                        callbacks.on_trial_error(trial=handle)
                        if isinstance(e, _UserLoopError):
                            raise TrainingFailedError(str(e)) from e
                        raise TrainingFailedError(
                            f"training failed after {failures} "
                            f"failure(s): {e}") from e
                    # restart from the latest persisted checkpoint
                    latest_ckpt = storage.latest_checkpoint() or latest_ckpt
        finally:
            callbacks.on_experiment_end(trials=[handle])

    # ------------------------------------------------------------------
    def _run_attempt(self, storage: StorageContext,
                     checkpoint: Optional[Checkpoint],
                     history: List[Dict[str, Any]],
                     callbacks=None, handle=None) -> Optional[Dict]:
        from ray_tpu.train.worker_group import WorkerGroup
        from ray_tpu.train.backend import _jax_env

        sc = self.scaling_config
        env = _jax_env(self.backend_config) \
            if isinstance(self.backend_config, JaxBackendConfig) else None
        group = WorkerGroup(
            sc.num_workers, sc.worker_resources(), storage.run_dir,
            placement_strategy=sc.placement_strategy, env=env,
            num_to_keep=self.run_config.checkpoint_config.num_to_keep)
        backend = self.backend_config.backend_cls()
        try:
            backend.on_start(group, self.backend_config)

            shards: Dict[int, Dict[str, Any]] = {
                i: {} for i in range(sc.num_workers)}
            for name, ds in self.datasets.items():
                for i, shard in enumerate(_shard_dataset(ds, sc.num_workers)):
                    shards[i][name] = shard

            backend.on_training_start(group, self.backend_config)
            ray_tpu.get([
                w.start_training.remote(
                    self.train_loop_per_worker, self.train_loop_config,
                    checkpoint.as_directory() if checkpoint else None,
                    shards[i], storage.name)
                for i, w in enumerate(group.workers)
            ], timeout=120)

            return self._poll_results(group, history,
                                      callbacks=callbacks, handle=handle)
        finally:
            try:
                backend.on_shutdown(group, self.backend_config)
            finally:
                group.shutdown()

    def _poll_results(self, group, history,
                      callbacks=None, handle=None) -> Optional[Dict]:
        finished = set()
        last_rank0: Optional[Dict] = None
        deadline_slack = 600.0  # no single poll may hang longer than this
        while len(finished) < group.num_workers:
            pending = [i for i in range(group.num_workers)
                       if i not in finished]
            refs = {i: group.workers[i].next_result.remote(2.0)
                    for i in pending}
            for i, ref in refs.items():
                item = ray_tpu.get(ref, timeout=deadline_slack)
                if item is None:
                    continue
                if item.get("finished"):
                    finished.add(i)
                    continue
                if "error" in item:
                    raise _UserLoopError(
                        f"rank {i} train loop failed:\n{item['traceback']}")
                if i == 0:
                    last_rank0 = item.get("metrics")
                    entry = dict(item.get("metrics") or {})
                    if item.get("checkpoint_path"):
                        entry["checkpoint_path"] = item["checkpoint_path"]
                        if callbacks is not None:
                            handle.last_checkpoint = \
                                item["checkpoint_path"]
                            callbacks.on_checkpoint(
                                trial=handle,
                                checkpoint_path=item["checkpoint_path"])
                    history.append(entry)
                    if callbacks is not None:
                        callbacks.on_trial_result(trial=handle,
                                                  result=entry)
            time.sleep(0.01)
        return last_rank0


@dataclasses.dataclass
class _RunHandle:
    """Trial-shaped view of a standalone train run for tune callbacks
    (same attribute surface loggers read: trial_id/trial_dir/config/
    metrics_history)."""

    trial_id: str
    trial_dir: str
    config: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    last_checkpoint: Optional[str] = None


class _UserLoopError(RuntimeError):
    """Training-loop exception (as opposed to infrastructure failure)."""


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer with the JAX backend by default (reference
    TorchTrainer ↔ DataParallelTrainer relationship, torch_trainer.py)."""

    def __init__(self, train_loop_per_worker, *,
                 backend_config: Optional[JaxBackendConfig] = None, **kw):
        super().__init__(
            train_loop_per_worker,
            backend_config=backend_config or JaxBackendConfig(), **kw)


class TorchTrainer(DataParallelTrainer):
    """DataParallelTrainer with the torch.distributed (gloo) backend
    (reference python/ray/train/torch/torch_trainer.py). Worker loops
    use train.torch_backend.prepare_model / prepare_data_loader."""

    def __init__(self, train_loop_per_worker, *, backend_config=None,
                 **kw):
        from ray_tpu.train.torch_backend import TorchConfig

        super().__init__(
            train_loop_per_worker,
            backend_config=backend_config or TorchConfig(), **kw)
