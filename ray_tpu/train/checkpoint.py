"""Checkpoint handle + pytree save/restore (orbax-backed).

Counterpart of the reference's train/_checkpoint.py `Checkpoint` (a directory
handle moved through pyarrow.fs) and train/_internal/storage.py
StorageContext.persist_current_checkpoint (:508).  TPU-native addition:
first-class JAX pytree (de)serialization via orbax, including sharded arrays —
restore takes an optional sharding tree so params land distributed, never
gathered to one host.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, Optional


class Checkpoint:
    """A directory of checkpoint data (framework-agnostic handle)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    # -- metrics sidecar ----------------------------------------------------
    def update_metadata(self, meta: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, ".metadata.json"), "w") as f:
            json.dump(meta, f)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, ".metadata.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


# ---------------------------------------------------------------------------
# JAX pytree persistence (orbax)
# ---------------------------------------------------------------------------

def _resolve_ckpt_path(directory: str, step: Optional[int]) -> str:
    """Shared sync/async step-directory naming (they must never
    diverge: a restore looks up whichever the save wrote)."""
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    return os.path.join(directory, f"step_{step}") if step is not None \
        else directory


def save_pytree(tree: Any, directory: str, *, step: Optional[int] = None,
                force: bool = True) -> str:
    """Save a JAX pytree (sharded arrays fine) under `directory`."""
    import orbax.checkpoint as ocp

    path = _resolve_ckpt_path(directory, step)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree, force=force)
    return path


class AsyncSave:
    """Handle for an in-flight async checkpoint (orbax
    AsyncCheckpointer): the device arrays were snapshotted at save();
    wait() blocks until the write is durable and releases the
    checkpointer's background resources. Keep the handle alive and
    ALWAYS wait() before relying on the checkpoint — there is no
    reliable non-blocking completion probe in orbax's public API."""

    def __init__(self, checkpointer, path: str):
        self._ckptr = checkpointer
        self.path = path

    def wait(self) -> str:
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
            try:
                self._ckptr.close()
            except Exception:
                pass
            self._ckptr = None  # idempotent; no leaked async manager
        return self.path


def save_pytree_async(tree: Any, directory: str, *,
                      step: Optional[int] = None,
                      force: bool = True) -> AsyncSave:
    """Start a non-blocking checkpoint save and return an AsyncSave.

    TPU-native checkpointing: orbax snapshots the arrays to host
    immediately and flushes to storage on background threads, so the
    train loop's next jitted step overlaps with checkpoint I/O instead
    of stalling on it (the reference's trainers block on upload)."""
    import orbax.checkpoint as ocp

    path = _resolve_ckpt_path(directory, step)
    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, args=ocp.args.StandardSave(tree), force=force)
    return AsyncSave(ckptr, path)


def load_pytree(path: str, *, target: Any = None,
                shardings: Any = None) -> Any:
    """Restore a pytree. With `shardings` (a pytree of NamedSharding),
    arrays are restored directly onto devices with that placement."""
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    if shardings is not None:
        import jax

        def spec(s):
            return ocp.ArrayRestoreArgs(sharding=s)

        restore_args = jax.tree.map(spec, shardings)
        return ckptr.restore(
            path, args=ocp.args.PyTreeRestore(
                restore_args=restore_args))
    if target is not None:
        return ckptr.restore(path, item=target)
    return ckptr.restore(path)


# ---------------------------------------------------------------------------
# Storage context: where run output lands (reference storage.py:352)
# ---------------------------------------------------------------------------

class StorageContext:
    """Filesystem layout for one run: storage_path/run_name/checkpoint_NNN."""

    def __init__(self, storage_path: Optional[str], name: Optional[str],
                 num_to_keep: Optional[int] = None):
        self.storage_path = os.path.abspath(
            storage_path or os.path.join(
                tempfile.gettempdir(), "ray_tpu_results"))
        self.name = name or f"run_{int(time.time())}"
        self.run_dir = os.path.join(self.storage_path, self.name)
        os.makedirs(self.run_dir, exist_ok=True)
        self.num_to_keep = num_to_keep
        # Resume-safe: continue numbering after any checkpoints already in
        # the run dir (a restarted attempt must never overwrite them).
        existing = self._list()
        self._seq = (
            int(os.path.basename(existing[-1]).split("_")[-1]) + 1
            if existing else 0)

    def persist_checkpoint(self, local_dir: str,
                           metrics: Optional[Dict] = None) -> Checkpoint:
        """Move a worker-local checkpoint dir into run storage."""
        dest = os.path.join(self.run_dir, f"checkpoint_{self._seq:06d}")
        self._seq += 1
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)
        ckpt = Checkpoint(dest)
        if metrics:
            ckpt.update_metadata({"metrics": metrics, "time": time.time()})
        self._gc()
        return ckpt

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        cks = self._list()
        return Checkpoint(cks[-1]) if cks else None

    def _list(self):
        if not os.path.isdir(self.run_dir):
            return []
        return sorted(
            os.path.join(self.run_dir, d) for d in os.listdir(self.run_dir)
            if d.startswith("checkpoint_"))

    def _gc(self):
        if self.num_to_keep is None:
            return
        cks = self._list()
        for old in cks[:max(0, len(cks) - self.num_to_keep)]:
            shutil.rmtree(old, ignore_errors=True)
