"""ray_tpu.train: distributed training library (reference: python/ray/train).

JaxTrainer runs a user loop on a worker group of actors; the JAX backend
joins them into one multi-process runtime (jax.distributed) so a single
jitted, mesh-sharded train step spans all workers' devices.
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxBackendConfig
from ray_tpu.train.checkpoint import (
    Checkpoint,
    StorageContext,
    load_pytree,
    save_pytree,
    save_pytree_async,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.train_loop_utils import (
    get_mesh,
    prepare_pytree,
    shard_batch,
)
from ray_tpu.train.trainer import (
    DataParallelTrainer,
    JaxTrainer,
    TorchTrainer,
    Result,
    TrainingFailedError,
)

__all__ = [
    "Backend", "BackendConfig", "JaxBackendConfig",
    "Checkpoint", "StorageContext", "save_pytree", "save_pytree_async", "load_pytree",
    "ScalingConfig", "RunConfig", "FailureConfig", "CheckpointConfig",
    "TrainContext", "report", "get_checkpoint", "get_context",
    "get_dataset_shard",
    "get_mesh", "prepare_pytree", "shard_batch",
    "DataParallelTrainer", "JaxTrainer", "TorchTrainer", "Result", "TrainingFailedError",
]

# Feature-usage tag (util/usage_stats.py; local-only, no egress).
from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("train")
del _rlu
