"""Memory-lean AdamW: independently typed first/second moments.

optax.adamw exposes mu_dtype only; at billion-params-on-one-chip scale
the fp32 second moment is another 4 B/param that decides whether the
fast "dots" remat policy fits HBM.  This is optax.scale_by_adam's
update rule with BOTH moments cast (nu in bf16 keeps fp32's exponent
range — it is a smooth EMA consumed through sqrt, so the 2^-8 relative
precision costs ~0.2% denominator noise; the trade the r1/r2 benches
already accepted for mu).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class ScaleByAdamState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any


def scale_by_adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                  mu_dtype=None, nu_dtype=None
                  ) -> optax.GradientTransformation:
    def _cast(tree, dtype):
        if dtype is None:
            return tree
        return jax.tree.map(lambda t: t.astype(dtype), tree)

    def init_fn(params):
        mu = _cast(jax.tree.map(jnp.zeros_like, params), mu_dtype)
        nu = _cast(jax.tree.map(jnp.zeros_like, params), nu_dtype)
        return ScaleByAdamState(jnp.zeros([], jnp.int32), mu, nu)

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        # Moment math in fp32, storage in the configured dtypes.
        mu = jax.tree.map(
            lambda g, m: b1 * m.astype(jnp.float32)
            + (1 - b1) * g.astype(jnp.float32), updates, state.mu)
        nu = jax.tree.map(
            lambda g, v: b2 * v.astype(jnp.float32)
            + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, v: (m / c1) / (jnp.sqrt(v / c2) + eps), mu, nu)
        return out, ScaleByAdamState(
            count, _cast(mu, mu_dtype), _cast(nu, nu_dtype))

    return optax.GradientTransformation(init_fn, update_fn)


def adamw(learning_rate, b1: float = 0.9, b2: float = 0.999,
          eps: float = 1e-8, weight_decay: float = 0.0,
          mu_dtype=None, nu_dtype=None) -> optax.GradientTransformation:
    """AdamW with typed moment storage (optax.adamw signature subset)."""
    return optax.chain(
        scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype,
                      nu_dtype=nu_dtype),
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_learning_rate(learning_rate),
    )
