"""Single-slot mutable shared-memory channel.

Capability counterpart of the reference's shared-memory channels backing
compiled DAGs (python/ray/experimental/channel/shared_memory_channel.py,
C++ mutable objects in core_worker/experimental_mutable_object_manager.cc;
raylet RPCs RegisterMutableObject/PushMutableObject,
node_manager.proto:440–442). The reference reuses plasma buffers made
mutable; here each channel is its own small mmap'ed file under the
session's shm dir — a fixed header plus a payload slot, rewritten in place
each write. This is the µs-latency actor→actor data plane that skips the
GCS/object-directory entirely.

Synchronization is seqlock-style: the writer bumps a sequence number after
writing; each reader acks the sequence it consumed; the writer blocks
until all readers acked the previous value (single-slot backpressure).
Cross-process waiting is bounded-backoff polling — at the message rates
compiled DAGs target (>10k msg/s) the slot is almost always ready and the
fast path is two shared-memory reads.

Values larger than the slot capacity spill to the object store
automatically: the slot then carries a (ref-hex, owner) pointer instead of
the payload (mirroring how the reference falls back from inlined to
plasma-backed transport).

TPU note: for device arrays, a channel carries host bytes; the jitted
consumer feeds them via jax.device_put. Intra-program stage handoff
belongs in XLA (collective-permute / donated buffers), not here — this
channel is for host-level pipeline orchestration.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional

from ray_tpu.core import serialization

_MAGIC = 0x7452FA11
# header: magic u32, closed u32, capacity u64, seq u64, msg_len u64,
#         kind u32, num_readers u32, reader_acks 16 × u64
_HDR_FMT = "<IIQQQII"
_HDR_LEN = struct.calcsize(_HDR_FMT)
_MAX_READERS = 16
_ACKS_OFF = _HDR_LEN
_PAYLOAD_OFF = _ACKS_OFF + 8 * _MAX_READERS

_KIND_INLINE = 0
_KIND_REF = 1

_POLL_MIN_S = 0.000005
_POLL_MAX_S = 0.0005


class ChannelClosedError(RuntimeError):
    pass


class ChannelTimeoutError(TimeoutError):
    pass


class Channel:
    """One endpoint of a single-writer / N-reader mutable shm channel.

    The driver creates the channel (``create=True``); endpoints on other
    processes attach by path. ``reader_idx`` selects this endpoint's ack
    slot; the writer passes ``reader_idx=None``.
    """

    def __init__(self, path: str, capacity: int = 1 << 20,
                 num_readers: int = 1, create: bool = False,
                 reader_idx: Optional[int] = None):
        if num_readers > _MAX_READERS:
            raise ValueError(f"at most {_MAX_READERS} readers per channel")
        self.path = path
        self.reader_idx = reader_idx
        if create:
            total = _PAYLOAD_OFF + capacity
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, total)
                self._f = os.fdopen(fd, "r+b")
            except Exception:
                os.close(fd)
                raise
            self._mm = mmap.mmap(self._f.fileno(), total)
            struct.pack_into(_HDR_FMT, self._mm, 0, _MAGIC, 0, capacity,
                             0, 0, _KIND_INLINE, num_readers)
        else:
            self._f = open(path, "r+b")
            size = os.fstat(self._f.fileno()).st_size
            self._mm = mmap.mmap(self._f.fileno(), size)
            magic = struct.unpack_from("<I", self._mm, 0)[0]
            if magic != _MAGIC:
                raise ValueError(f"{path} is not a channel file")
        (_, _, self.capacity, _, _, _, self.num_readers
         ) = struct.unpack_from(_HDR_FMT, self._mm, 0)

    # -- low-level header accessors -------------------------------------
    def _seq(self) -> int:
        return struct.unpack_from("<Q", self._mm, 16)[0]

    def _set_seq(self, v: int):
        struct.pack_into("<Q", self._mm, 16, v)

    def _closed(self) -> bool:
        return struct.unpack_from("<I", self._mm, 4)[0] != 0

    def _ack(self, idx: int) -> int:
        return struct.unpack_from("<Q", self._mm, _ACKS_OFF + 8 * idx)[0]

    def _set_ack(self, idx: int, v: int):
        struct.pack_into("<Q", self._mm, _ACKS_OFF + 8 * idx, v)

    def _wait(self, cond, timeout: Optional[float], what: str):
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = _POLL_MIN_S
        while not cond():
            if self._closed():
                raise ChannelClosedError(f"channel {self.path} closed")
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"timed out waiting to {what} on {self.path}")
            time.sleep(delay)
            delay = min(delay * 2, _POLL_MAX_S)

    # -- API -------------------------------------------------------------
    def _wait_writable(self, timeout: Optional[float]) -> int:
        """Single-slot backpressure shared by every transport tier:
        block until all readers acked the previous value; returns the
        sequence number to publish under."""
        seq = self._seq()
        self._wait(
            lambda: all(self._ack(i) >= seq for i in range(self.num_readers)),
            timeout, "write")
        return seq

    def write(self, value: Any, timeout: Optional[float] = None):
        """Write the next value; blocks until every reader consumed the
        previous one (single-slot backpressure)."""
        seq = self._wait_writable(timeout)
        ser = serialization.serialize(value)
        n = ser.total_bytes
        kind = _KIND_INLINE
        if n > self.capacity:
            # payload too big for the slot: spill through the object store
            from ray_tpu.core.runtime import get_runtime

            ref = get_runtime().put(value)
            blob = f"{ref.hex()}:{ref.owner or ''}".encode()
            self._mm[_PAYLOAD_OFF:_PAYLOAD_OFF + len(blob)] = blob
            n = len(blob)
            kind = _KIND_REF
            self._spill_ref = ref  # keep alive until overwritten
        else:
            ser.write_into(
                memoryview(self._mm)[_PAYLOAD_OFF:_PAYLOAD_OFF + n])
        struct.pack_into("<Q", self._mm, 24, n)       # msg_len
        struct.pack_into("<I", self._mm, 32, kind)    # kind
        self._set_seq(seq + 1)

    def read(self, timeout: Optional[float] = None) -> Any:
        """Read the next value (each reader sees every value exactly once)."""
        if self.reader_idx is None:
            raise RuntimeError("writer endpoint cannot read")
        my = self._ack(self.reader_idx)
        self._wait(lambda: self._seq() > my, timeout, "read")
        n = struct.unpack_from("<Q", self._mm, 24)[0]
        kind = struct.unpack_from("<I", self._mm, 32)[0]
        raw = bytes(self._mm[_PAYLOAD_OFF:_PAYLOAD_OFF + n])
        if kind == _KIND_REF:
            from ray_tpu.core.ids import ObjectID
            from ray_tpu.core.object_ref import ObjectRef
            from ray_tpu.core.runtime import get_runtime

            obj_hex, _, owner = raw.decode().partition(":")
            rt = get_runtime()
            rt.core.client.send({"op": "incref", "obj": obj_hex})
            value = rt.get(
                [ObjectRef(ObjectID.from_hex(obj_hex), owner or None)])[0]
        else:
            value = serialization.deserialize(raw)
        self._set_ack(self.reader_idx, my + 1)
        return value

    def close(self):
        """Mark closed; all blocked/future reads and writes raise."""
        try:
            struct.pack_into("<I", self._mm, 4, 1)
        except ValueError:
            pass  # mmap already unmapped

    def destroy(self):
        self.close()
        try:
            self._mm.close()
            self._f.close()
        except (BufferError, OSError, ValueError):
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __reduce__(self):
        # endpoints are reconstructed on the receiving process; reader_idx
        # is assigned by the DAG compiler per consumer
        return (Channel, (self.path, self.capacity, self.num_readers,
                          False, self.reader_idx))
