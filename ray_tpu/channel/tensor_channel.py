"""Device-tensor channel tier for compiled DAGs.

Counterpart of the reference's NCCL channel tier
(python/ray/experimental/channel/torch_tensor_nccl_channel.py +
torch_tensor_type.py): a `.with_tensor_transport()` hint on a DAG node
switches that node's output edges to a TENSOR protocol — no pickle
anywhere on the hot path.  Two transports, chosen per message:

  - DEVICE-NATIVE (zero host copies): when every reader of the edge
    lives in the writer's process — the TPU-normal topology, one host
    process driving all local chips through one XLA client
    (dag/device_stage.py stages) — the shm slot carries only a frame;
    the jax.Arrays hand over through the process-local registry
    (channel/device_registry.py) and land on the consumer's device via
    `jax.device_put`, a chip-to-chip ICI copy.  The reference needs
    NCCL for this because its stages are separate processes per GPU;
    the JAX client makes the same capability a d2d transfer.
    Asserted host-transfer-free by
    tests/test_dag.py::test_device_native_dag_zero_host_copies under
    jax transfer guards.
  - HOST-SHM (explicit fallback): cross-process consumers get raw
    array bytes + a fixed struct header in the slot (producer
    np.asarray -> shm; consumer np.frombuffer view -> device_put).

Supports a single array or a flat tuple/list of arrays per message.
"""

from __future__ import annotations

import struct
from typing import Any, Optional

import numpy as np

from ray_tpu.channel import device_registry
from ray_tpu.channel.shared_memory_channel import (
    _PAYLOAD_OFF,
    Channel,
)

# payload layout (kind 2, host bytes): u32 count, then per tensor:
#   u32 dtype_len, dtype bytes, u32 ndim, u64 x ndim shape, u64 nbytes,
#   raw buffer
# payload layout (kind 3, device token): u32 count (arrays live in the
#   process-local registry keyed by (path, seq))
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

_KIND_TENSOR_BYTES = 2
_KIND_TENSOR_TOKEN = 3


class TensorType:
    """Edge hint: values on this edge are device tensors; move them via
    the tensor protocol instead of pickle (reference
    experimental/channel/torch_tensor_type.py).

    transport: "auto" picks device-native when the edge's endpoints
    share a process, host-shm otherwise; "shm" forces the host path."""

    def __init__(self, transport: str = "auto", device: str = "auto"):
        self.transport = transport
        self.device = device

    def __repr__(self):
        return f"TensorType(transport={self.transport!r})"


_jax_array_type = None


def _is_jax_array(a) -> bool:
    global _jax_array_type
    if _jax_array_type is None:
        try:
            import jax

            _jax_array_type = jax.Array
        except Exception:  # noqa: BLE001
            _jax_array_type = ()  # jax absent: nothing ever matches
    return isinstance(a, _jax_array_type)


class DeviceTensorChannel(Channel):
    """Channel endpoint speaking the tensor protocol."""

    def __init__(self, *args, device=None, transport: str = "auto",
                 **kwargs):
        super().__init__(*args, **kwargs)
        self._device = device
        self._transport = transport
        self._registered = False
        if self.reader_idx is not None:
            device_registry.register_reader(self.path)
            self._registered = True

    def close(self):
        if self._registered:
            device_registry.unregister_reader(self.path)
            self._registered = False
        super().close()

    def destroy(self):
        device_registry.purge(self.path)
        super().destroy()

    # -- write ----------------------------------------------------------
    def write(self, value: Any, timeout: Optional[float] = None):
        arrays = value if isinstance(value, (tuple, list)) else (value,)
        if not all(hasattr(a, "dtype") and hasattr(a, "shape")
                   for a in arrays):
            # Non-tensor payload (e.g. a DagExecutionError envelope from
            # a failing stage): fall back to the pickle protocol; the
            # reader dispatches on the kind field.
            return Channel.write(self, value, timeout)
        if (self._transport != "shm"
                and device_registry.local_reader_count(self.path)
                >= self.num_readers
                and all(_is_jax_array(a) for a in arrays)):
            return self._write_token(arrays, timeout)
        return self._write_bytes(arrays, timeout)

    def _write_token(self, arrays, timeout):
        """Device-native handoff: frame through shm, arrays through the
        process-local registry — the payload never touches the host."""
        seq = self._wait_writable(timeout)
        device_registry.publish(self.path, seq, tuple(arrays),
                                self.num_readers)
        mm = self._mm
        _U32.pack_into(mm, _PAYLOAD_OFF, len(arrays))
        struct.pack_into("<Q", mm, 24, _U32.size)  # msg_len
        struct.pack_into("<I", mm, 32, _KIND_TENSOR_TOKEN)
        self._set_seq(seq + 1)

    def _write_bytes(self, arrays, timeout):
        hosts = [np.asarray(a) for a in arrays]  # device->host DMA
        total = _U32.size
        metas = []
        for h in hosts:
            dt = np.dtype(h.dtype).str.encode()
            total += _U32.size + len(dt) + _U32.size \
                + _U64.size * h.ndim + _U64.size + h.nbytes
            metas.append(dt)
        if total > self.capacity:
            raise ValueError(
                f"tensor message of {total} bytes exceeds channel "
                f"capacity {self.capacity}; size the DAG's "
                "buffer_size_bytes for the largest stage output")
        seq = self._wait_writable(timeout)
        mm = self._mm
        off = _PAYLOAD_OFF
        _U32.pack_into(mm, off, len(hosts))
        off += _U32.size
        for h, dt in zip(hosts, metas):
            _U32.pack_into(mm, off, len(dt))
            off += _U32.size
            mm[off:off + len(dt)] = dt
            off += len(dt)
            _U32.pack_into(mm, off, h.ndim)
            off += _U32.size
            for d in h.shape:
                _U64.pack_into(mm, off, d)
                off += _U64.size
            _U64.pack_into(mm, off, h.nbytes)
            off += _U64.size
            mv = memoryview(np.ascontiguousarray(h)).cast("B")
            mm[off:off + h.nbytes] = mv
            off += h.nbytes
        struct.pack_into("<Q", mm, 24, off - _PAYLOAD_OFF)  # msg_len
        struct.pack_into("<I", mm, 32, _KIND_TENSOR_BYTES)
        self._set_seq(seq + 1)

    # -- read -----------------------------------------------------------
    def read(self, timeout: Optional[float] = None) -> Any:
        if self.reader_idx is None:
            raise RuntimeError("writer endpoint cannot read")
        my = self._ack(self.reader_idx)
        self._wait(lambda: self._seq() > my, timeout, "read")
        (kind,) = _U32.unpack_from(self._mm, 32)
        if kind == _KIND_TENSOR_TOKEN:
            return self._read_token(my)
        if kind != _KIND_TENSOR_BYTES:
            # Pickle-protocol payload (error envelope — possibly
            # ref-spilled): the base reader handles inline AND spilled
            # kinds and acks; the slot is still unread for us, so its
            # wait returns immediately.
            return Channel.read(self, timeout)
        return self._read_bytes(my)

    def _read_token(self, my: int) -> Any:
        import jax

        value = device_registry.take(self.path, my)
        if value is None:
            raise RuntimeError(
                f"device-token message {my} on {self.path} has no "
                "registry entry in this process — writer/reader "
                "locality handshake broken")
        out = []
        for a in value:
            if self._device is not None \
                    and a.device != self._device:
                # Chip-to-chip placement (ICI d2d) — no host staging.
                a = jax.device_put(a, self._device)
            else:
                # Same device (or unpinned endpoint): an on-device copy
                # insulates the consumer from writer-side donation or
                # reuse — without it the consumer would hold the
                # WRITER's buffer, and a jit(donate_argnums=...) in
                # either stage would delete it under the other.
                import jax.numpy as jnp

                a = jnp.copy(a)
            out.append(a)
        # The d2d copy must complete before the ack releases the slot:
        # the writer may overwrite/donate its buffer next iteration.
        for a in out:
            jax.block_until_ready(a)
        self._set_ack(self.reader_idx, my + 1)
        return out[0] if len(out) == 1 else tuple(out)

    def _read_bytes(self, my: int) -> Any:
        import jax

        mm = self._mm
        off = _PAYLOAD_OFF
        (count,) = _U32.unpack_from(mm, off)
        off += _U32.size
        out = []
        for _ in range(count):
            (dt_len,) = _U32.unpack_from(mm, off)
            off += _U32.size
            dtype = np.dtype(bytes(mm[off:off + dt_len]).decode())
            off += dt_len
            (ndim,) = _U32.unpack_from(mm, off)
            off += _U32.size
            shape = []
            for _ in range(ndim):
                (d,) = _U64.unpack_from(mm, off)
                off += _U64.size
                shape.append(d)
            (nbytes,) = _U64.unpack_from(mm, off)
            off += _U64.size
            host = np.frombuffer(
                mm, dtype=dtype, count=int(np.prod(shape, dtype=np.int64))
                if shape else 1, offset=off).reshape(shape)
            off += nbytes
            # host view -> this process's device; the copy happens in
            # the transfer engine, never through pickle.
            dev = self._device or jax.devices()[0]
            if dev.platform == "cpu":
                # CPU backend may alias the numpy buffer — and the slot
                # is recycled after the ack — so copy out of the mmap.
                host = host.copy()
            out.append(jax.device_put(host, dev))
        # The H2D DMA must complete before the ack releases the slot to
        # the writer, or the next message overwrites bytes mid-transfer.
        for a in out:
            jax.block_until_ready(a)
        self._set_ack(self.reader_idx, my + 1)
        return out[0] if count == 1 else tuple(out)
