"""Device-tensor channel tier for compiled DAGs.

Counterpart of the reference's NCCL channel tier
(python/ray/experimental/channel/torch_tensor_nccl_channel.py +
torch_tensor_type.py): a `.with_tensor_transport()` hint on a DAG node
switches that node's output edges to a TENSOR protocol — no pickle
anywhere on the hot path.  v1 is host-mediated (the VERDICT's
"jax.device_put between jitted steps"): the producer DMAs the device
array to host (np.asarray) and copies raw bytes + a fixed struct header
straight into the mutable shm slot; the consumer views the slot memory
(np.frombuffer, zero-copy) and `jax.device_put`s it onto its own
device, ready for the next jitted stage.  On a multi-chip runtime the
same hint upgrades to ICI send/recv compiled into the stage programs;
the channel protocol (header + raw payload) is transport-agnostic.

Supports a single array or a flat tuple/list of arrays per message.
"""

from __future__ import annotations

import struct
from typing import Any, Optional

import numpy as np

from ray_tpu.channel.shared_memory_channel import (
    _PAYLOAD_OFF,
    Channel,
)

# payload layout: u32 count, then per tensor:
#   u32 dtype_len, dtype bytes, u32 ndim, u64 x ndim shape, u64 nbytes,
#   raw buffer
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class TensorType:
    """Edge hint: values on this edge are device tensors; move them via
    the tensor protocol instead of pickle (reference
    experimental/channel/torch_tensor_type.py)."""

    def __init__(self, transport: str = "auto", device: str = "auto"):
        self.transport = transport
        self.device = device

    def __repr__(self):
        return f"TensorType(transport={self.transport!r})"


class DeviceTensorChannel(Channel):
    """Channel endpoint speaking the raw-tensor protocol."""

    def __init__(self, *args, device=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._device = device

    # -- write ----------------------------------------------------------
    def write(self, value: Any, timeout: Optional[float] = None):
        arrays = value if isinstance(value, (tuple, list)) else (value,)
        if not all(hasattr(a, "dtype") and hasattr(a, "shape")
                   for a in arrays):
            # Non-tensor payload (e.g. a DagExecutionError envelope from
            # a failing stage): fall back to the pickle protocol; the
            # reader dispatches on the kind field.
            return Channel.write(self, value, timeout)
        hosts = [np.asarray(a) for a in arrays]  # device->host DMA
        total = _U32.size
        metas = []
        for h in hosts:
            dt = np.dtype(h.dtype).str.encode()
            total += _U32.size + len(dt) + _U32.size \
                + _U64.size * h.ndim + _U64.size + h.nbytes
            metas.append(dt)
        if total > self.capacity:
            raise ValueError(
                f"tensor message of {total} bytes exceeds channel "
                f"capacity {self.capacity}; size the DAG's "
                "buffer_size_bytes for the largest stage output")
        seq = self._seq()
        self._wait(
            lambda: all(self._ack(i) >= seq
                        for i in range(self.num_readers)),
            timeout, "write")
        mm = self._mm
        off = _PAYLOAD_OFF
        _U32.pack_into(mm, off, len(hosts))
        off += _U32.size
        for h, dt in zip(hosts, metas):
            _U32.pack_into(mm, off, len(dt))
            off += _U32.size
            mm[off:off + len(dt)] = dt
            off += len(dt)
            _U32.pack_into(mm, off, h.ndim)
            off += _U32.size
            for d in h.shape:
                _U64.pack_into(mm, off, d)
                off += _U64.size
            _U64.pack_into(mm, off, h.nbytes)
            off += _U64.size
            mv = memoryview(np.ascontiguousarray(h)).cast("B")
            mm[off:off + h.nbytes] = mv
            off += h.nbytes
        struct.pack_into("<Q", mm, 24, off - _PAYLOAD_OFF)  # msg_len
        struct.pack_into("<I", mm, 32, 2)  # kind: tensor protocol
        self._set_seq(seq + 1)

    # -- read -----------------------------------------------------------
    def read(self, timeout: Optional[float] = None) -> Any:
        if self.reader_idx is None:
            raise RuntimeError("writer endpoint cannot read")
        my = self._ack(self.reader_idx)
        self._wait(lambda: self._seq() > my, timeout, "read")
        (kind,) = _U32.unpack_from(self._mm, 32)
        if kind != 2:
            # Pickle-protocol payload (error envelope — possibly
            # ref-spilled): the base reader handles inline AND spilled
            # kinds and acks; the slot is still unread for us, so its
            # wait returns immediately.
            return Channel.read(self, timeout)
        import jax

        mm = self._mm
        off = _PAYLOAD_OFF
        (count,) = _U32.unpack_from(mm, off)
        off += _U32.size
        out = []
        for _ in range(count):
            (dt_len,) = _U32.unpack_from(mm, off)
            off += _U32.size
            dtype = np.dtype(bytes(mm[off:off + dt_len]).decode())
            off += dt_len
            (ndim,) = _U32.unpack_from(mm, off)
            off += _U32.size
            shape = []
            for _ in range(ndim):
                (d,) = _U64.unpack_from(mm, off)
                off += _U64.size
                shape.append(d)
            (nbytes,) = _U64.unpack_from(mm, off)
            off += _U64.size
            host = np.frombuffer(
                mm, dtype=dtype, count=int(np.prod(shape, dtype=np.int64))
                if shape else 1, offset=off).reshape(shape)
            off += nbytes
            # host view -> this process's device; the copy happens in
            # the transfer engine, never through pickle.
            dev = self._device or jax.devices()[0]
            if dev.platform == "cpu":
                # CPU backend may alias the numpy buffer — and the slot
                # is recycled after the ack — so copy out of the mmap.
                host = host.copy()
            out.append(jax.device_put(host, dev))
        # The H2D DMA must complete before the ack releases the slot to
        # the writer, or the next message overwrites bytes mid-transfer.
        for a in out:
            jax.block_until_ready(a)
        self._set_ack(self.reader_idx, my + 1)
        return out[0] if count == 1 else tuple(out)
