"""Mutable shared-memory channels for compiled DAG execution.

Capability counterpart of the reference's ray.experimental.channel
(python/ray/experimental/channel/shared_memory_channel.py and the C++
mutable-object manager, core_worker/experimental_mutable_object_manager.cc).
"""

from ray_tpu.channel.shared_memory_channel import (
    Channel,
    ChannelClosedError,
    ChannelTimeoutError,
)
from ray_tpu.channel.tensor_channel import DeviceTensorChannel, TensorType

__all__ = ["Channel", "ChannelClosedError", "ChannelTimeoutError",
           "DeviceTensorChannel", "TensorType"]
