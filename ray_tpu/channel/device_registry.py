"""Process-local device-array handoff registry for tensor channels.

The device-native channel tier (tensor_channel.py) moves jax.Arrays
between pipeline stages WITHOUT host staging when both endpoints live in
one process — the TPU-normal topology: one host process drives all its
local chips through a single XLA client, so a pipeline stage handoff is
a chip-to-chip `jax.device_put` over ICI.  (The reference reaches the
same capability with one process per GPU bridged by NCCL,
python/ray/experimental/channel/nccl_group.py:19 — on TPU that shape
would forfeit the single-client d2d path, so the process boundary moves
to the host.)

The shm slot still carries the message FRAME (sequencing, backpressure,
error envelopes); only the array payload bypasses it: the writer
publishes the device arrays here keyed by (channel path, seq) and
readers in the same process take them directly.  Writers decide per
message: the token mode is only used when EVERY reader of the channel
has registered from this process, so a cross-process consumer always
gets the host-bytes fallback and can never see an unresolvable token.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Tuple

_lock = threading.Lock()
_readers: Dict[str, int] = {}          # path -> local reader endpoints
_entries: Dict[Tuple[str, int], list] = {}  # (path, seq) -> [value, refs]


def register_reader(path: str) -> None:
    with _lock:
        _readers[path] = _readers.get(path, 0) + 1


def unregister_reader(path: str) -> None:
    with _lock:
        n = _readers.get(path, 0) - 1
        if n <= 0:
            _readers.pop(path, None)
        else:
            _readers[path] = n


def local_reader_count(path: str) -> int:
    with _lock:
        return _readers.get(path, 0)


def publish(path: str, seq: int, value: Any, nreaders: int) -> None:
    with _lock:
        _entries[(path, seq)] = [value, nreaders]


def take(path: str, seq: int):
    """Fetch the published value for (path, seq); the entry is dropped
    once every reader took it.  Returns None when absent (the writer
    used the bytes fallback for this message)."""
    with _lock:
        ent = _entries.get((path, seq))
        if ent is None:
            return None
        ent[1] -= 1
        if ent[1] <= 0:
            del _entries[(path, seq)]
        return ent[0]


def purge(path: str) -> None:
    """Drop any unconsumed entries for a channel (teardown)."""
    with _lock:
        for key in [k for k in _entries if k[0] == path]:
            del _entries[key]
