"""External-library searcher adapters: Ax, Nevergrad, HEBO, ZOOpt,
HyperOpt.

Counterpart of the reference's python/ray/tune/search/{ax,nevergrad,
hebo,zoopt,hyperopt}/ adapters.  Each maps search.py domains onto the library's
own ask/tell surface and implements the in-tree `Searcher` protocol
(searchers.py), so `as_search_algorithm` plugs any of them into the
Tuner.  None of the libraries ship in the air-gapped image: every
adapter raises ImportError with guidance toward the native in-tree
equivalent (TPE / BOHB / PB2 / BasicVariant), takes a `_module=`
injection point, and is exercised against protocol-faithful stubs in
tests/test_tune_searchers.py — where the real package exists, the same
code activates unchanged.

Domain mapping rules shared by all adapters:
  - Uniform / QUniform  -> continuous range (q rounded after ask)
  - LogUniform          -> log-scaled continuous range
  - RandInt / LogRandInt-> integer range (high exclusive, like
                           search.py's samplers)
  - RandN               -> continuous range mean +- 4 sd (libraries
                           without a normal prior)
  - Choice / GridSearch -> categorical
  - SampleFrom          -> resolved locally after the library's ask
                           (depends on the sampled config)
  - plain values        -> passed through untouched
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search import (
    Choice,
    Domain,
    GridSearch,
    LogRandInt,
    LogUniform,
    QUniform,
    RandInt,
    RandN,
    SampleFrom,
    Uniform,
    _set_path,
    _walk,
)
from ray_tpu.tune.searchers import Searcher


def _missing(pkg: str, native: str):
    return ImportError(
        f"{pkg} is not installed (pip install {pkg}); in the "
        f"air-gapped image use the native in-tree equivalent: {native}")


def _dims(space) -> List[Tuple[Tuple[str, ...], Any]]:
    """(path, leaf) for every tunable leaf, skipping SampleFrom.
    GridSearch is NOT a Domain subclass (search.py treats grids as an
    enumeration directive, not a sampler) but external optimizers see
    it as a categorical, so it is included explicitly."""
    return [(path, leaf) for path, leaf in _walk(space or {})
            if (isinstance(leaf, Domain)
                and not isinstance(leaf, SampleFrom))
            or isinstance(leaf, GridSearch)]


def _assemble(space, sampled: Dict[str, Any]) -> Dict[str, Any]:
    """Merge library-sampled values (keyed by dotted path) with the
    constant / SampleFrom parts of the space."""
    cfg: Dict[str, Any] = {}
    deferred = []
    for path, leaf in _walk(space or {}):
        name = ".".join(path)
        if isinstance(leaf, SampleFrom):
            deferred.append((path, leaf))
        elif name in sampled:
            _set_path(cfg, path, sampled[name])
        else:
            _set_path(cfg, path, leaf)
    for path, leaf in deferred:
        _set_path(cfg, path, leaf.fn(cfg))
    return cfg


def _bounds(leaf) -> Tuple[float, float, bool, bool]:
    """(low, high, is_int, log) for range-typed domains."""
    if isinstance(leaf, LogUniform):
        return float(leaf.low), float(leaf.high), False, True
    if isinstance(leaf, (Uniform, QUniform)):
        return float(leaf.low), float(leaf.high), False, False
    if isinstance(leaf, LogRandInt):
        return float(leaf.low), float(max(leaf.low, leaf.high - 1)), \
            True, True
    if isinstance(leaf, RandInt):
        return float(leaf.low), float(max(leaf.low, leaf.high - 1)), \
            True, False
    if isinstance(leaf, RandN):
        return leaf.mean - 4 * leaf.sd, leaf.mean + 4 * leaf.sd, \
            False, False
    raise TypeError(f"not a range domain: {leaf!r}")


def _postprocess(leaf, value):
    """Round q-quantized and integer domains after the library's ask."""
    if isinstance(leaf, QUniform):
        return round(round(float(value) / leaf.q) * leaf.q, 10)
    if isinstance(leaf, (RandInt, LogRandInt)):
        return int(round(float(value)))
    return value


class AxSearch(Searcher):
    """Adapter over Ax's Service API (reference
    tune/search/ax/ax_search.py): AxClient.create_experiment with typed
    parameter dicts, get_next_trial -> complete_trial."""

    def __init__(self, ax_client=None, _module=None):
        if ax_client is None and _module is None:
            try:
                from ax.service.ax_client import AxClient  # noqa: PLC0415

                _module = AxClient
            except ImportError as e:
                raise _missing(
                    "ax-platform",
                    "PB2 (native GP-bandit, ray_tpu.tune.PB2) or "
                    "TPESearcher") from e
        self._client = ax_client if ax_client is not None else _module()
        self._trials: Dict[str, int] = {}
        self._space = {}
        self._leaves: Dict[str, Any] = {}
        self._metric = None

    def set_search_properties(self, metric, mode, space):
        self._metric, self._space = metric, space or {}
        params = []
        for path, leaf in _dims(self._space):
            name = ".".join(path)
            self._leaves[name] = leaf
            if isinstance(leaf, (Choice, GridSearch)):
                params.append({"name": name, "type": "choice",
                               "values": list(leaf.values)})
            else:
                lo, hi, is_int, log = _bounds(leaf)
                params.append({
                    "name": name, "type": "range",
                    "bounds": [int(lo), int(hi)] if is_int
                    else [lo, hi],
                    "value_type": "int" if is_int else "float",
                    "log_scale": log,
                })
        self._client.create_experiment(
            name="ray_tpu_tune", parameters=params,
            objective_name=metric, minimize=(mode == "min"))
        return True

    def suggest(self, trial_id):
        params, index = self._client.get_next_trial()
        self._trials[trial_id] = index
        sampled = {k: _postprocess(self._leaves[k], v)
                   for k, v in params.items() if k in self._leaves}
        return _assemble(self._space, sampled)

    def on_trial_complete(self, trial_id, result=None, error=False):
        index = self._trials.pop(trial_id, None)
        if index is None:
            return
        if error or not result or self._metric not in result:
            try:
                self._client.log_trial_failure(index)
            except Exception:
                pass
            return
        self._client.complete_trial(
            index, raw_data={self._metric:
                             (float(result[self._metric]), 0.0)})


class NevergradSearch(Searcher):
    """Adapter over nevergrad's ask/tell optimizers (reference
    tune/search/nevergrad/nevergrad_search.py): a parametrization Dict
    of Scalar/Log/Choice instruments, optimizer.ask() -> .tell()."""

    def __init__(self, optimizer: Optional[str] = "NGOpt", budget=None,
                 _module=None):
        if _module is None:
            try:
                import nevergrad  # noqa: PLC0415

                _module = nevergrad
            except ImportError as e:
                raise _missing(
                    "nevergrad",
                    "TPESearcher or BasicVariantGenerator") from e
        self._ng = _module
        self._optimizer_name = optimizer
        self._budget = budget
        self._opt = None
        self._space = {}
        self._leaves: Dict[str, Any] = {}
        self._metric = None
        self._mode = "max"
        self._candidates: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, space):
        self._metric, self._mode, self._space = metric, mode, space or {}
        ng = self._ng
        instruments = {}
        for path, leaf in _dims(self._space):
            name = ".".join(path)
            self._leaves[name] = leaf
            if isinstance(leaf, (Choice, GridSearch)):
                instruments[name] = ng.p.Choice(list(leaf.values))
            else:
                lo, hi, is_int, log = _bounds(leaf)
                scalar = ng.p.Log(lower=lo, upper=hi) if log \
                    else ng.p.Scalar(lower=lo, upper=hi)
                if is_int:
                    scalar = scalar.set_integer_casting()
                instruments[name] = scalar
        param = ng.p.Dict(**instruments)
        opt_cls = getattr(ng.optimizers, self._optimizer_name)
        self._opt = opt_cls(parametrization=param, budget=self._budget)
        return True

    def suggest(self, trial_id):
        cand = self._opt.ask()
        self._candidates[trial_id] = cand
        sampled = {k: _postprocess(self._leaves[k], v)
                   for k, v in cand.value.items()}
        return _assemble(self._space, sampled)

    def on_trial_complete(self, trial_id, result=None, error=False):
        cand = self._candidates.pop(trial_id, None)
        if cand is None or error or not result \
                or self._metric not in result:
            return
        value = float(result[self._metric])
        # nevergrad minimizes.
        self._opt.tell(cand, -value if self._mode == "max" else value)


class HEBOSearch(Searcher):
    """Adapter over HEBO's DataFrame ask/tell (reference
    tune/search/hebo/hebo_search.py): DesignSpace.parse of typed
    variable dicts, suggest() -> observe()."""

    def __init__(self, _module=None):
        if _module is None:
            try:
                import hebo.optimizers.hebo as hebo_mod  # noqa: PLC0415
                from hebo.design_space.design_space import (  # noqa
                    DesignSpace,
                )

                _module = (hebo_mod.HEBO, DesignSpace)
            except ImportError as e:
                raise _missing(
                    "HEBO", "PB2 (native GP-bandit) or BOHBSearcher"
                ) from e
        self._hebo_cls, self._space_cls = _module
        self._opt = None
        self._space = {}
        self._leaves: Dict[str, Any] = {}
        self._metric = None
        self._mode = "max"
        self._pending: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, space):
        self._metric, self._mode, self._space = metric, mode, space or {}
        specs = []
        for path, leaf in _dims(self._space):
            name = ".".join(path)
            self._leaves[name] = leaf
            if isinstance(leaf, (Choice, GridSearch)):
                specs.append({"name": name, "type": "cat",
                              "categories": list(leaf.values)})
            else:
                lo, hi, is_int, log = _bounds(leaf)
                if is_int:
                    specs.append({"name": name, "type": "int",
                                  "lb": int(lo), "ub": int(hi)})
                elif log:
                    specs.append({"name": name, "type": "pow",
                                  "lb": lo, "ub": hi})
                else:
                    specs.append({"name": name, "type": "num",
                                  "lb": lo, "ub": hi})
        self._opt = self._hebo_cls(self._space_cls().parse(specs))
        return True

    def suggest(self, trial_id):
        rec = self._opt.suggest(n_suggestions=1)
        self._pending[trial_id] = rec
        row = rec.iloc[0].to_dict()
        sampled = {k: _postprocess(self._leaves[k], v)
                   for k, v in row.items() if k in self._leaves}
        return _assemble(self._space, sampled)

    def on_trial_complete(self, trial_id, result=None, error=False):
        import numpy as np

        rec = self._pending.pop(trial_id, None)
        if rec is None or error or not result \
                or self._metric not in result:
            return
        value = float(result[self._metric])
        # HEBO minimizes.
        y = -value if self._mode == "max" else value
        self._opt.observe(rec, np.asarray([[y]]))


class ZOOptSearch(Searcher):
    """Adapter over ZOOpt (reference tune/search/zoopt/zoopt_search.py).

    ZOOpt's public surface is solve-oriented (`Opt.min(objective,
    parameter)` drives the loop), so the adapter INVERTS it: the solve
    loop runs on a daemon thread whose objective function blocks
    handing each solution to `suggest` and waits for
    `on_trial_complete` to report the value — the classic
    loop-inversion bridge between solve-style optimizers and ask/tell
    schedulers."""

    def __init__(self, budget: int = 100, _module=None):
        if _module is None:
            try:
                import zoopt  # noqa: PLC0415

                _module = zoopt
            except ImportError as e:
                raise _missing(
                    "zoopt", "TPESearcher or BOHBSearcher") from e
        self._zoopt = _module
        self._budget = budget
        self._space = {}
        self._leaves: List[Tuple[str, Any]] = []
        self._metric = None
        self._mode = "max"
        import queue
        import threading

        self._asks = queue.Queue(maxsize=1)
        self._tells: Dict[int, Any] = {}
        self._tell_cv = threading.Condition()
        self._pending: Dict[str, Tuple[int, Any]] = {}
        self._next_ask = 0
        self._thread = None

    def set_search_properties(self, metric, mode, space):
        self._metric, self._mode, self._space = metric, mode, space or {}
        z = self._zoopt
        dims = []
        self._leaves = []
        for path, leaf in _dims(self._space):
            name = ".".join(path)
            self._leaves.append((name, leaf))
            if isinstance(leaf, (Choice, GridSearch)):
                # Categoricals become an index dimension.
                dims.append(([0, len(leaf.values) - 1], False))
            else:
                lo, hi, is_int, _log = _bounds(leaf)
                dims.append(([lo, hi], not is_int))

        def objective(solution):
            xs = solution.get_x()
            idx = self._enqueue(xs)
            return self._await_tell(idx)

        dim = z.Dimension(len(dims), [d[0] for d in dims],
                          [d[1] for d in dims])
        obj = z.Objective(objective, dim)
        par = z.Parameter(budget=self._budget)
        import threading

        self._thread = threading.Thread(
            target=lambda: z.Opt.min(obj, par), daemon=True,
            name="zoopt-solve")
        self._thread.start()
        return True

    def _enqueue(self, xs) -> int:
        with self._tell_cv:
            idx = self._next_ask
            self._next_ask += 1
        self._asks.put((idx, xs))
        return idx

    def _await_tell(self, idx: int) -> float:
        with self._tell_cv:
            while idx not in self._tells:
                self._tell_cv.wait(timeout=1.0)
            return self._tells.pop(idx)

    def suggest(self, trial_id):
        """ZOOpt's sequential RACOS proposes ONE solution at a time
        (the solve thread blocks in the objective until the previous
        trial reports), so with a trial in flight this returns None
        immediately — the controller retries after completions instead
        of stalling its loop.  None with nothing in flight and a dead
        solve thread means the budget is exhausted."""
        import queue
        import time

        try:
            idx, xs = self._asks.get_nowait()
        except queue.Empty:
            if self._pending:
                return None  # a solution is in flight; ask again later
            deadline = time.monotonic() + 5.0
            idx = None
            while time.monotonic() < deadline:
                try:
                    idx, xs = self._asks.get(timeout=0.2)
                    break
                except queue.Empty:
                    if self._thread is None \
                            or not self._thread.is_alive():
                        return None  # budget exhausted
            if idx is None:
                return None
        sampled = {}
        for (name, leaf), value in zip(self._leaves, xs):
            if isinstance(leaf, (Choice, GridSearch)):
                sampled[name] = list(leaf.values)[int(round(value))]
            else:
                sampled[name] = _postprocess(leaf, value)
        self._pending[trial_id] = (idx, xs)
        return _assemble(self._space, sampled)

    def on_trial_complete(self, trial_id, result=None, error=False):
        ent = self._pending.pop(trial_id, None)
        if ent is None:
            return
        idx, _ = ent
        if error or not result or self._metric not in result:
            value = float("inf")  # zoopt minimizes; a failure is worst
        else:
            v = float(result[self._metric])
            value = -v if self._mode == "max" else v
        with self._tell_cv:
            self._tells[idx] = value
            self._tell_cv.notify_all()


class HyperOptSearch(Searcher):
    """Adapter over HyperOpt's Trials store + suggest algorithm
    (reference tune/search/hyperopt/hyperopt_search.py).

    HyperOpt has no ask/tell optimizer object — the `Trials` store IS
    the protocol: new trial docs come from
    `algo(new_ids, domain, trials, seed)` (tpe.suggest by default), get
    inserted into the store, and results are reported by mutating the
    doc's state/result in place followed by `trials.refresh()`.
    Sampled values are read from the doc's misc vals
    (`base.spec_from_misc`); `hp.choice` dims store the INDEX there, so
    the adapter maps indices back through the in-tree Choice values
    itself instead of evaluating the domain expression the way the
    reference does with memo tricks.
    """

    def __init__(self, n_initial_points: Optional[int] = None,
                 random_state_seed: int = 0, _module=None):
        if _module is None:
            try:
                import hyperopt  # noqa: PLC0415

                _module = hyperopt
            except ImportError as e:
                raise _missing(
                    "hyperopt",
                    "TPESearcher (native TPE — the same algorithm "
                    "family — ray_tpu.tune.TPESearcher)") from e
        self._hpo = _module
        self._algo = _module.tpe.suggest
        if n_initial_points is not None:
            import functools

            self._algo = functools.partial(
                _module.tpe.suggest, n_startup_jobs=n_initial_points)
        import numpy as _np

        self._rng = _np.random.default_rng(random_state_seed)
        self._store = None
        self._domain = None
        self._space = {}
        self._leaves: Dict[str, Any] = {}
        self._live: Dict[str, Any] = {}
        self._metric = None
        self._mode = "max"

    def set_search_properties(self, metric, mode, space):
        self._metric, self._mode, self._space = metric, mode, space or {}
        hp = self._hpo.hp
        import math as _math

        dims = {}
        for path, leaf in _dims(self._space):
            name = ".".join(path)
            self._leaves[name] = leaf
            if isinstance(leaf, (Choice, GridSearch)):
                # Values stay adapter-side: misc vals carry the index.
                dims[name] = hp.choice(name, list(range(
                    len(list(leaf.values)))))
            elif isinstance(leaf, RandN):
                dims[name] = hp.normal(name, leaf.mean, leaf.sd)
            elif isinstance(leaf, QUniform):
                dims[name] = hp.quniform(name, leaf.low, leaf.high,
                                         leaf.q)
            else:
                lo, hi, is_int, log = _bounds(leaf)
                if is_int and log:
                    dims[name] = hp.qloguniform(
                        name, _math.log(max(lo, 1e-12)),
                        _math.log(max(hi, 1e-12)), 1)
                elif is_int:
                    dims[name] = hp.quniform(name, lo, hi, 1)
                elif log:
                    # hyperopt's loguniform takes LOG-space bounds.
                    dims[name] = hp.loguniform(
                        name, _math.log(lo), _math.log(hi))
                else:
                    dims[name] = hp.uniform(name, lo, hi)
        self._store = self._hpo.Trials()
        self._domain = self._hpo.Domain(lambda spc: 0, dims)
        return True

    def suggest(self, trial_id):
        trials = self._store
        new_ids = trials.new_trial_ids(1)
        trials.refresh()
        docs = self._algo(new_ids, self._domain, trials,
                          int(self._rng.integers(2 ** 31 - 1)))
        trials.insert_trial_docs(docs)
        trials.refresh()
        doc = docs[0]
        self._live[trial_id] = doc
        vals = self._hpo.base.spec_from_misc(doc["misc"])
        sampled = {}
        for name, leaf in self._leaves.items():
            if name not in vals:
                continue
            v = vals[name]
            if isinstance(leaf, (Choice, GridSearch)):
                sampled[name] = list(leaf.values)[int(v)]
            else:
                sampled[name] = _postprocess(leaf, v)
        return _assemble(self._space, sampled)

    def on_trial_complete(self, trial_id, result=None, error=False):
        live = self._live.pop(trial_id, None)
        if live is None:
            return
        # Mutate the doc IN THE STORE, not the pre-insert original:
        # the real library's insert_trial_docs stores a SONify'd deep
        # copy, so updates to the original would never reach TPE (the
        # reference adapter looks its doc up by tid the same way).
        doc = next((t for t in self._store.trials
                    if t["tid"] == live["tid"]), live)
        base = self._hpo.base
        if error or not result or self._metric not in result:
            doc["state"] = base.JOB_STATE_ERROR
            doc["misc"]["error"] = ("ray_tpu.tune", "trial failed")
        else:
            v = float(result[self._metric])
            # hyperopt minimizes loss.
            doc["state"] = base.JOB_STATE_DONE
            doc["result"] = {
                "loss": -v if self._mode == "max" else v,
                "status": "ok"}
        self._store.refresh()
