"""TuneController: the trial-driving event loop.

Counterpart of python/ray/tune/execution/tune_controller.py (TuneController
:68; step() :666 schedules trial actors :964, consumes results, applies
scheduler decisions, checkpoints experiment state).  Trials run as
TrialRunner actors; the loop polls next_result, feeds the scheduler, and
executes STOP/PAUSE(+PBT exploit) decisions.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.trainer import Result
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import CONTINUE, PAUSE, STOP, TrialScheduler
from ray_tpu.tune.search import SearchAlgorithm
from ray_tpu.tune.trainable import TrialRunner

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    trial_dir: str
    state: str = PENDING
    runner: Any = None
    last_result: Optional[Dict[str, Any]] = None
    metrics_history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    last_checkpoint: Optional[str] = None
    error: Optional[str] = None
    num_failures: int = 0
    rungs_seen: Dict[int, bool] = dataclasses.field(default_factory=dict)
    exploit_directive: Optional[Dict[str, Any]] = None

    def best_metric(self, metric: str, mode: str) -> Optional[float]:
        vals = [r[metric] for r in self.metrics_history if metric in r]
        if not vals:
            return None
        return max(vals) if mode == "max" else min(vals)


class TuneController:
    def __init__(self, trainable, *, search_alg: SearchAlgorithm,
                 scheduler: TrialScheduler, num_samples: int,
                 metric: Optional[str], mode: str,
                 max_concurrent: int, run_dir: str,
                 stop: Optional[Any] = None,
                 max_failures: int = 0,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 callbacks: Optional[List[Any]] = None):
        from ray_tpu.tune.callbacks import default_callbacks

        self._callbacks = default_callbacks(callbacks)
        self._trainable = trainable
        self._search = search_alg
        self._scheduler = scheduler
        self._scheduler.set_objective(metric or "_", mode)
        self._metric = metric
        self._mode = mode
        self._max_concurrent = max(1, max_concurrent)
        self._run_dir = run_dir
        self._stop = stop
        self._max_failures = max_failures
        self._resources = resources_per_trial or {"num_cpus": 1.0}
        os.makedirs(run_dir, exist_ok=True)

        # Trials are created LAZILY so adaptive searchers (TPE) see
        # completed results before suggesting the next configs — the
        # reference's suggest-on-demand loop rather than drawing the
        # whole experiment up front.
        self._num_samples = num_samples
        self.trials: List[Trial] = []

    def _maybe_create_trials(self):
        active = sum(1 for t in self.trials
                     if t.state in (PENDING, RUNNING))
        want = min(self._num_samples - len(self.trials),
                   self._max_concurrent - active)
        if want <= 0:
            return
        configs = self._search.next_configs(want)
        if not configs and active == 0:
            # Searcher is dry with nothing in flight: the experiment is
            # as large as it will get (prevents a livelock on exhausted
            # finite searchers).
            self._num_samples = len(self.trials)
            return
        on_add = getattr(self._scheduler, "on_trial_add", None)
        for cfg in configs:
            i = len(self.trials)
            t = Trial(
                trial_id=f"trial_{i:05d}", config=cfg,
                trial_dir=os.path.join(self._run_dir, f"trial_{i:05d}"))
            self.trials.append(t)
            if on_add is not None:
                on_add(t)
        if configs:
            self._configs_dirty = True

    # ------------------------------------------------------------------
    def run(self) -> List[Trial]:
        self._callbacks.setup(run_dir=self._run_dir, trials=self.trials)
        try:
            while (len(self.trials) < self._num_samples
                   or any(t.state in (PENDING, RUNNING, PAUSED)
                          for t in self.trials)):
                self._maybe_create_trials()
                self._apply_unpause_decisions()
                self._start_pending()
                self._poll_running()
                self._save_experiment_state()
        finally:
            for t in self.trials:
                self._shutdown_runner(t)
            self._save_experiment_state()
            self._callbacks.on_experiment_end(trials=self.trials)
        return self.trials

    # ------------------------------------------------------------------
    def _start_pending(self):
        running = sum(1 for t in self.trials if t.state == RUNNING)
        for t in self.trials:
            if running >= self._max_concurrent:
                break
            if t.state != PENDING:
                continue
            self._start_trial(t)
            running += 1

    def _start_trial(self, t: Trial, checkpoint_path: Optional[str] = None):
        opts: Dict[str, Any] = {"max_concurrency": 4}
        if "num_cpus" in self._resources:
            opts["num_cpus"] = self._resources["num_cpus"]
        if "num_tpus" in self._resources:
            opts["num_tpus"] = self._resources["num_tpus"]
        # Wrap at the call site (module attr must stay the plain class so
        # cloudpickle serializes it by reference, not by value).
        runner_cls = ray_tpu.remote(**opts)(TrialRunner)
        t.runner = runner_cls.remote(
            self._trainable, t.config, t.trial_id, t.trial_dir,
            checkpoint_path or t.last_checkpoint)
        t.state = RUNNING
        self._callbacks.on_trial_start(trial=t)

    def _shutdown_runner(self, t: Trial):
        if t.runner is not None:
            try:
                ray_tpu.get(t.runner.stop.remote(), timeout=5)
            except Exception:
                pass
            try:
                ray_tpu.kill(t.runner)
            except Exception:
                pass
            t.runner = None

    # ------------------------------------------------------------------
    def _poll_running(self):
        running = [t for t in self.trials if t.state == RUNNING]
        if not running:
            return
        refs = {t.trial_id: t.runner.next_result.remote(0.5)
                for t in running}
        for t in running:
            try:
                item = ray_tpu.get(refs[t.trial_id], timeout=600)
            except Exception:
                self._on_trial_error(t, traceback.format_exc())
                continue
            if item is None:
                continue
            if item.get("error"):
                self._on_trial_error(t, item.get("traceback", ""))
                continue
            if item.get("finished"):
                self._complete(t)
                continue
            self._on_result(t, item)

    def _on_result(self, t: Trial, item: Dict[str, Any]):
        metrics = item["metrics"]
        if item.get("checkpoint_path"):
            t.last_checkpoint = item["checkpoint_path"]
            metrics = dict(metrics)
            metrics["checkpoint_path"] = item["checkpoint_path"]
            self._callbacks.on_checkpoint(
                trial=t, checkpoint_path=item["checkpoint_path"])
        t.last_result = metrics
        t.metrics_history.append(metrics)
        self._callbacks.on_trial_result(trial=t, result=metrics)

        if self._should_stop(t.trial_id, metrics):
            self._complete(t)
            return
        decision = self._scheduler.on_trial_result(t, metrics)
        if decision == STOP:
            self._complete(t)
        elif decision == PAUSE and t.exploit_directive:
            self._exploit(t)
        elif decision == PAUSE:
            self._pause(t)

    def _save_runner_checkpoint(self, t: Trial, timeout: float
                                ) -> Optional[str]:
        """Save a trial's runner (class trainables; function trainables
        return None), record it, and fire on_checkpoint — the one
        bookkeeping path for every controller-initiated save."""
        if t.runner is None:
            return None
        try:
            path = ray_tpu.get(t.runner.save.remote(), timeout=timeout)
        except Exception:
            return None
        if path:
            t.last_checkpoint = path
            self._callbacks.on_checkpoint(trial=t, checkpoint_path=path)
        return path

    def _pause(self, t: Trial):
        """Checkpoint + release the runner; the trial waits for the
        scheduler's unpause decision (synchronous HyperBand rungs —
        reference hyperband.py pauses trials at rung boundaries)."""
        self._save_runner_checkpoint(t, timeout=60)
        self._shutdown_runner(t)
        t.state = PAUSED

    def _apply_unpause_decisions(self):
        """Ask the scheduler about paused trials (schedulers without
        rung barriers never pause, so this is a no-op for them)."""
        poll = getattr(self._scheduler, "poll_paused", None)
        if poll is None:
            return
        for trial_id, decision in (poll() or {}).items():
            t = next((x for x in self.trials
                      if x.trial_id == trial_id), None)
            if t is None or t.state != PAUSED:
                continue
            if decision == STOP:
                t.state = TERMINATED
                self._search.on_trial_complete(
                    t.trial_id, t.last_result, config=t.config)
                self._scheduler.on_trial_complete(t, t.last_result)
            else:  # CONTINUE: resume from own checkpoint
                t.state = PENDING

    def _should_stop(self, trial_id: str, metrics: Dict[str, Any]) -> bool:
        stop = self._stop
        if stop is None:
            return False
        if callable(stop):
            return bool(stop(trial_id, metrics))
        if isinstance(stop, dict):
            return any(k in metrics and metrics[k] >= v
                       for k, v in stop.items())
        return False

    def _complete(self, t: Trial):
        # Snapshot class trainables so the final state is recoverable.
        self._save_runner_checkpoint(t, timeout=30)
        self._shutdown_runner(t)
        t.state = TERMINATED
        self._search.on_trial_complete(t.trial_id, t.last_result,
                                       config=t.config)
        self._scheduler.on_trial_complete(t, t.last_result)
        self._callbacks.on_trial_complete(trial=t)

    def _on_trial_error(self, t: Trial, tb: str):
        t.num_failures += 1
        self._shutdown_runner(t)
        if t.num_failures <= self._max_failures:
            # retry from the last checkpoint (FailureConfig semantics)
            self._start_trial(t)
            return
        t.error = tb
        t.state = ERROR
        self._search.on_trial_complete(t.trial_id, None, error=True,
                                       config=t.config)
        self._callbacks.on_trial_error(trial=t)

    def _exploit(self, t: Trial):
        """PBT: restart this trial from the donor's checkpoint with the
        explored config (pbt.py _exploit)."""
        directive = t.exploit_directive or {}
        t.exploit_directive = None
        donor = next((d for d in self.trials
                      if d.trial_id == directive.get("donor")), None)
        if donor is None:
            return
        donor_ckpt = (self._save_runner_checkpoint(donor, timeout=60)
                      or donor.last_checkpoint)
        if donor_ckpt is None:
            return
        self._shutdown_runner(t)
        t.config = dict(directive.get("config") or t.config)
        self._configs_dirty = True
        self._start_trial(t, checkpoint_path=donor_ckpt)

    # ------------------------------------------------------------------
    def _save_experiment_state(self):
        # Lossless config sidecar: the JSON state stringifies non-JSON
        # config values, which would corrupt re-run trials on restore.
        # Rewritten only when a config changed (trial created / PBT
        # exploit), not on every poll tick.
        if getattr(self, "_configs_dirty", True):
            try:
                import pickle

                with open(os.path.join(self._run_dir,
                                       ".trial_configs.pkl"), "wb") as f:
                    pickle.dump({t.trial_id: t.config for t in self.trials},
                                f)
                self._configs_dirty = False
            except Exception:
                pass
        state = {
            "timestamp": time.time(),
            "num_samples": self._num_samples,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": _json_safe(t.config),
                    "state": t.state,
                    "last_result": _json_safe(t.last_result),
                    "last_checkpoint": t.last_checkpoint,
                    "num_failures": t.num_failures,
                    "error": t.error,
                }
                for t in self.trials
            ],
        }
        tmp = os.path.join(self._run_dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(
            self._run_dir, "experiment_state.json"))


def _json_safe(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)


def trials_to_results(trials: List[Trial]) -> List[Result]:
    out = []
    for t in trials:
        out.append(Result(
            metrics=t.last_result or {},
            checkpoint=(Checkpoint(t.last_checkpoint)
                        if t.last_checkpoint else None),
            path=t.trial_dir,
            metrics_history=t.metrics_history,
            error=t.error,
        ))
    return out
