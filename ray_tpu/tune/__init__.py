"""ray_tpu.tune: distributed hyperparameter tuning.

Counterpart of python/ray/tune (SURVEY.md §2.3 L3): Tuner → TuneController
event loop over trial actors, search spaces/algorithms, ASHA/median/PBT
schedulers, experiment state on disk.
"""

from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    HyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.pb2 import PB2
from ray_tpu.tune.external_searchers import (
    AxSearch,
    HEBOSearch,
    HyperOptSearch,
    NevergradSearch,
    ZOOptSearch,
)
from ray_tpu.tune.searchers import (
    OptunaSearch,
    Searcher,
    as_search_algorithm,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    BOHBSearcher,
    TPESearcher,
    SearchAlgorithm,
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import (
    Trainable,
    get_checkpoint,
    get_trial_dir,
    get_trial_id,
    report,
)
from ray_tpu.tune.callbacks import (
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    TBXLoggerCallback,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner

__all__ = [
    "Callback",
    "CSVLoggerCallback",
    "JsonLoggerCallback",
    "TBXLoggerCallback",
    "Tuner",
    "TuneConfig",
    "ResultGrid",
    "Trainable",
    "report",
    "get_checkpoint",
    "get_trial_id",
    "get_trial_dir",
    "grid_search",
    "choice",
    "uniform",
    "quniform",
    "loguniform",
    "randint",
    "lograndint",
    "randn",
    "sample_from",
    "SearchAlgorithm",
    "BasicVariantGenerator", "TPESearcher", "BOHBSearcher", "ConcurrencyLimiter",
    "Searcher", "OptunaSearch", "as_search_algorithm",
    "AxSearch", "NevergradSearch", "HEBOSearch", "ZOOptSearch",
    "HyperOptSearch",
    "TrialScheduler",
    "FIFOScheduler",
    "AsyncHyperBandScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "PB2",
]

# Feature-usage tag (util/usage_stats.py; local-only, no egress).
from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("tune")
del _rlu
