"""Tune callbacks + logger callbacks.

Counterpart of the reference's python/ray/tune/callback.py (Callback
hook surface dispatched from TuneController) and tune/logger/
(JsonLoggerCallback json.py, CSVLoggerCallback csv.py,
TBXLoggerCallback tensorboardx.py).  Hook names match the reference so
user callbacks port verbatim; dispatch points live in
tune_controller.py.  Loggers write per-trial files into each trial's
own directory (result.json / progress.csv), the layout downstream
tooling expects.

TBX is gated exactly like tune/external_searchers.py: tensorboardX is
not in the air-gapped image, so the adapter raises a guiding
ImportError, takes `_module=` for protocol-faithful stub tests, and
activates unchanged where the real package exists.  The experiment
trackers (wandb/mlflow/comet) live in util/integrations.py.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np


class Callback:
    """Experiment-loop hooks (reference tune/callback.py Callback).

    The controller calls these synchronously from its event loop; state
    kept on the callback is safe without locks."""

    def setup(self, *, run_dir: str, trials: List[Any]) -> None:
        """Once, before the loop starts (trials may still be empty —
        they are created lazily as searchers suggest)."""

    def on_trial_start(self, *, trial) -> None:
        pass

    def on_trial_result(self, *, trial, result: Dict[str, Any]) -> None:
        pass

    def on_checkpoint(self, *, trial, checkpoint_path: str) -> None:
        pass

    def on_trial_complete(self, *, trial) -> None:
        pass

    def on_trial_error(self, *, trial) -> None:
        pass

    def on_experiment_end(self, *, trials: List[Any]) -> None:
        pass


class CallbackList(Callback):
    """Fan-out dispatcher; one misbehaving callback must not kill the
    experiment loop, so hook errors are contained and reported once."""

    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])
        self._failed: set = set()

    def _each(self, hook: str, **kwargs) -> None:
        for cb in self.callbacks:
            try:
                getattr(cb, hook)(**kwargs)
            except Exception as e:  # noqa: BLE001 - contain user bugs
                key = (id(cb), hook)
                if key not in self._failed:
                    self._failed.add(key)
                    import logging

                    logging.getLogger("ray_tpu.tune").warning(
                        "callback %s.%s raised %r (suppressed; further "
                        "errors from this hook are silent)",
                        type(cb).__name__, hook, e)

    def setup(self, **kw):
        self._each("setup", **kw)

    def on_trial_start(self, **kw):
        self._each("on_trial_start", **kw)

    def on_trial_result(self, **kw):
        self._each("on_trial_result", **kw)

    def on_checkpoint(self, **kw):
        self._each("on_checkpoint", **kw)

    def on_trial_complete(self, **kw):
        self._each("on_trial_complete", **kw)

    def on_trial_error(self, **kw):
        self._each("on_trial_error", **kw)

    def on_experiment_end(self, **kw):
        self._each("on_experiment_end", **kw)


# ---------------------------------------------------------------------------
# Logger callbacks
# ---------------------------------------------------------------------------


def _scalarize(value: Any) -> Any:
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, np.ndarray) and value.ndim == 0:
        return value.item()
    return value


def _flatten(metrics: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in metrics.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, f"{key}/"))
        else:
            out[key] = _scalarize(v)
    return out


class JsonLoggerCallback(Callback):
    """One JSON line per reported result → <trial_dir>/result.json
    (reference tune/logger/json.py)."""

    FILE = "result.json"

    def on_trial_result(self, *, trial, result: Dict[str, Any]) -> None:
        os.makedirs(trial.trial_dir, exist_ok=True)
        row = _flatten(result)
        row.setdefault("trial_id", trial.trial_id)
        with open(os.path.join(trial.trial_dir, self.FILE), "a") as f:
            f.write(json.dumps(row, default=str) + "\n")


class CSVLoggerCallback(Callback):
    """Tabular per-trial progress → <trial_dir>/progress.csv (reference
    tune/logger/csv.py).  The header is fixed by the FIRST result's
    keys; later keys not in the header are dropped, matching the
    reference's behavior."""

    FILE = "progress.csv"

    def __init__(self):
        self._fields: Dict[str, List[str]] = {}

    def on_trial_result(self, *, trial, result: Dict[str, Any]) -> None:
        os.makedirs(trial.trial_dir, exist_ok=True)
        row = _flatten(result)
        row.setdefault("trial_id", trial.trial_id)
        path = os.path.join(trial.trial_dir, self.FILE)
        if trial.trial_id not in self._fields:
            # An existing non-empty file means a restored experiment:
            # adopt ITS header instead of writing a second one mid-file.
            if os.path.exists(path) and os.path.getsize(path) > 0:
                with open(path, newline="") as f:
                    self._fields[trial.trial_id] = next(csv.reader(f))
            else:
                self._fields[trial.trial_id] = list(row)
                with open(path, "a", newline="") as f:
                    csv.DictWriter(
                        f, fieldnames=self._fields[trial.trial_id]
                    ).writeheader()
        with open(path, "a", newline="") as f:
            csv.DictWriter(
                f, fieldnames=self._fields[trial.trial_id],
                extrasaction="ignore").writerow(row)


class TBXLoggerCallback(Callback):
    """TensorBoard event files via tensorboardX (reference
    tune/logger/tensorboardx.py); numeric scalars only."""

    def __init__(self, _module=None):
        if _module is None:
            try:
                import tensorboardX as _module  # noqa: N813
            except ImportError:
                raise ImportError(
                    "tensorboardX is not installed (pip install "
                    "tensorboardX); in the air-gapped image use "
                    "JsonLoggerCallback / CSVLoggerCallback") from None
        self._tbx = _module
        self._writers: Dict[str, Any] = {}

    def on_trial_result(self, *, trial, result: Dict[str, Any]) -> None:
        writer = self._writers.get(trial.trial_id)
        if writer is None:
            writer = self._tbx.SummaryWriter(logdir=trial.trial_dir)
            self._writers[trial.trial_id] = writer
        step = int(result.get("training_iteration",
                              len(trial.metrics_history)))
        for k, v in _flatten(result).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            writer.add_scalar(k, v, global_step=step)
        writer.flush()

    def on_trial_complete(self, *, trial) -> None:
        writer = self._writers.pop(trial.trial_id, None)
        if writer is not None:
            writer.close()

    def on_experiment_end(self, *, trials) -> None:
        for writer in self._writers.values():
            writer.close()
        self._writers.clear()


DEFAULT_LOGGERS = (JsonLoggerCallback, CSVLoggerCallback)


def default_callbacks(user: Optional[List[Callback]] = None
                      ) -> CallbackList:
    """User callbacks plus the default JSON/CSV loggers — unless the
    user already supplied that logger class themselves (reference
    tune/utils/callback.py _create_default_callbacks)."""
    cbs: List[Callback] = list(user or [])
    for cls in DEFAULT_LOGGERS:
        # isinstance, not type equality: a user's subclassed logger
        # already covers the role (the reference's
        # _create_default_callbacks does the same).
        if not any(isinstance(cb, cls) for cb in cbs):
            cbs.append(cls())
    return CallbackList(cbs)
