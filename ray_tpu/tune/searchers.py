"""Ask/tell Searcher protocol + external-searcher integrations.

Counterpart of python/ray/tune/search/searcher.py (the per-trial
ask/tell `Searcher` interface external libraries implement) and
python/ray/tune/search/optuna/optuna_search.py (the reference's Optuna
adapter).  The internal planner interface stays SearchAlgorithm
(search.py — batch `next_configs`); `as_search_algorithm` adapts any
Searcher onto it, so one adapter covers every ask/tell integration.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search import (
    Choice,
    Domain,
    GridSearch,
    LogRandInt,
    LogUniform,
    QUniform,
    RandInt,
    RandN,
    SampleFrom,
    SearchAlgorithm,
    Uniform,
    _set_path,
    _walk,
)


class Searcher:
    """Per-trial ask/tell interface (reference tune/search/searcher.py).

    Implementations return one config per `suggest(trial_id)` and learn
    from `on_trial_complete(trial_id, result, error)`.  Return None from
    suggest() to signal exhaustion."""

    def set_search_properties(self, metric: Optional[str], mode: str,
                              space: Dict[str, Any]) -> bool:
        self._metric, self._mode, self._space = metric, mode, space
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass


class _SearcherAdapter(SearchAlgorithm):
    """Adapts an ask/tell Searcher onto the internal SearchAlgorithm
    (batch) protocol: generates trial ids for suggestions; completions
    route back via the __searcher_trial_id__ key carried in each
    suggested config."""

    def __init__(self, searcher: Searcher):
        self.searcher = searcher

    def set_space(self, space, metric, mode):
        self.searcher.set_search_properties(metric, mode, space or {})

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        out = []
        for _ in range(n):
            tid = uuid.uuid4().hex[:8]
            cfg = self.searcher.suggest(tid)
            if cfg is None:
                break
            cfg = dict(cfg)
            cfg["__searcher_trial_id__"] = tid
            out.append(cfg)
        return out

    def on_trial_complete(self, trial_id, result, error=False, config=None):
        tid = (config or {}).get("__searcher_trial_id__") or trial_id
        self.searcher.on_trial_complete(tid, result=result, error=error)


def as_search_algorithm(searcher) -> SearchAlgorithm:
    """Wrap an ask/tell Searcher for Tuner(search_alg=...); passes
    SearchAlgorithm instances through unchanged."""
    if isinstance(searcher, SearchAlgorithm):
        return searcher
    return _SearcherAdapter(searcher)


class OptunaSearch(Searcher):
    """Optuna integration via its ask/tell API (reference
    tune/search/optuna/optuna_search.py).  Maps search.py domains onto
    optuna distributions; raises ImportError with guidance when optuna
    is not installed (this image has no egress — the adapter is tested
    with a stub and activates automatically where optuna exists)."""

    def __init__(self, sampler=None, seed: Optional[int] = None,
                 _optuna_module=None):
        if _optuna_module is not None:
            self._optuna = _optuna_module
        else:
            try:
                import optuna  # noqa: PLC0415

                self._optuna = optuna
            except ImportError as e:
                raise ImportError(
                    "OptunaSearch requires the `optuna` package "
                    "(pip install optuna); in the air-gapped image use "
                    "TPESearcher (ray_tpu.tune.TPESearcher), the native "
                    "equivalent of optuna's default TPE sampler") from e
        self._sampler = sampler
        self._seed = seed
        self._study = None
        self._trials: Dict[str, Any] = {}
        self._dims: List = []
        self._metric = None
        self._mode = "max"
        self._space: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, space):
        self._metric, self._mode, self._space = metric, mode, space or {}
        direction = "minimize" if mode == "min" else "maximize"
        sampler = self._sampler
        if sampler is None and hasattr(self._optuna, "samplers"):
            try:
                sampler = self._optuna.samplers.TPESampler(seed=self._seed)
            except Exception:
                sampler = None
        self._study = self._optuna.create_study(
            direction=direction, sampler=sampler)
        self._dims = [
            (path, leaf) for path, leaf in _walk(self._space)
            if isinstance(leaf, Domain) and not isinstance(leaf, SampleFrom)
        ]
        return True

    def _suggest_leaf(self, trial, name: str, leaf):
        if isinstance(leaf, LogUniform):
            return trial.suggest_float(name, leaf.low, leaf.high, log=True)
        if isinstance(leaf, Uniform):
            return trial.suggest_float(name, leaf.low, leaf.high)
        if isinstance(leaf, QUniform):
            return trial.suggest_float(name, leaf.low, leaf.high,
                                       step=leaf.q)
        if isinstance(leaf, LogRandInt):
            return trial.suggest_int(name, leaf.low, max(leaf.low,
                                                         leaf.high - 1),
                                     log=True)
        if isinstance(leaf, RandInt):
            return trial.suggest_int(name, leaf.low, max(leaf.low,
                                                         leaf.high - 1))
        if isinstance(leaf, RandN):
            # No native normal distribution: approximate with +-4 sd.
            return trial.suggest_float(name, leaf.mean - 4 * leaf.sd,
                                       leaf.mean + 4 * leaf.sd)
        if isinstance(leaf, Choice):
            return trial.suggest_categorical(name, list(leaf.values))
        return None

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        trial = self._study.ask()
        self._trials[trial_id] = trial
        cfg: Dict[str, Any] = {}
        deferred = []
        for path, leaf in _walk(self._space):
            name = ".".join(path)
            if isinstance(leaf, SampleFrom):
                deferred.append((path, leaf))
            elif isinstance(leaf, GridSearch):
                # Grids become categoricals under optuna's sampler.
                _set_path(cfg, path,
                          trial.suggest_categorical(name,
                                                    list(leaf.values)))
            elif isinstance(leaf, Domain):
                _set_path(cfg, path, self._suggest_leaf(trial, name, leaf))
            else:
                _set_path(cfg, path, leaf)
        for path, leaf in deferred:
            _set_path(cfg, path, leaf.fn(cfg))
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        trial = self._trials.pop(trial_id, None)
        if trial is None or self._study is None:
            return
        if error or not result or self._metric not in result:
            state = getattr(self._optuna.trial, "TrialState", None)
            try:
                self._study.tell(trial, state=state.FAIL
                                 if state is not None else None)
            except Exception:
                pass
            return
        self._study.tell(trial, float(result[self._metric]))
