"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Counterpart of python/ray/tune/schedulers/ (async_hyperband.py
AsyncHyperBandScheduler, median_stopping_rule.py, pbt.py
PopulationBasedTraining).  The controller calls on_trial_result for every
result and acts on the returned decision.
"""

from __future__ import annotations

import dataclasses
import math
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def set_objective(self, metric: str, mode: str):
        self._metric = metric
        self._mode = mode

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict[str, Any]]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (python/ray/tune/schedulers/async_hyperband.py): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung stops
    unless its score is in the top 1/reduction_factor of that rung."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100):
        self._time_attr = time_attr
        self._grace = grace_period
        self._rf = reduction_factor
        self._max_t = max_t
        self._rungs: Dict[int, List[float]] = defaultdict(list)

    def _rung_levels(self) -> List[int]:
        levels = []
        t = self._grace
        while t < self._max_t:
            levels.append(int(t))
            t *= self._rf
        return levels

    def on_trial_result(self, trial, result):
        t = result.get(self._time_attr)
        score = self._score(result)
        if t is None or score is None:
            return CONTINUE
        if t >= self._max_t:
            return STOP
        for level in self._rung_levels():
            if t >= level and level not in trial.rungs_seen:
                trial.rungs_seen[level] = score
                self._rungs[level].append(score)
        # A trial that joined a rung before it filled escapes the arrival
        # check (async ASHA's optimistic promotion); re-check its recorded
        # rung scores against the current cutoffs so stragglers still stop.
        for level, my in sorted(trial.rungs_seen.items(), reverse=True):
            rung = self._rungs[level]
            if len(rung) >= self._rf:
                cutoff = float(np.quantile(rung, 1.0 - 1.0 / self._rf))
                if my < cutoff:
                    return STOP
                break  # passed its highest filled rung
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score is worse than the median of other
    trials' running means at the same step
    (python/ray/tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._means: Dict[str, List[float]] = defaultdict(list)

    def on_trial_result(self, trial, result):
        t = result.get(self._time_attr, 0)
        score = self._score(result)
        if score is None:
            return CONTINUE
        self._means[trial.trial_id].append(score)
        if t < self._grace:
            return CONTINUE
        others = [float(np.mean(v)) for tid, v in self._means.items()
                  if tid != trial.trial_id and v]
        if len(others) < self._min_samples:
            return CONTINUE
        my_best = max(self._means[trial.trial_id])
        if my_best < float(np.median(others)):
            return STOP
        return CONTINUE


@dataclasses.dataclass
class _PbtState:
    last_perturb_t: int = 0


class PopulationBasedTraining(TrialScheduler):
    """PBT (python/ray/tune/schedulers/pbt.py): every
    perturbation_interval, bottom-quantile trials exploit a top-quantile
    trial's checkpoint and explore (perturb) its hyperparameters.  The
    controller executes the returned exploit directive."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_p = resample_probability
        self._rng = np.random.default_rng(seed)
        self._state: Dict[str, _PbtState] = defaultdict(_PbtState)
        self._latest: Dict[str, float] = {}
        self._trials: Dict[str, Any] = {}

    def on_trial_result(self, trial, result):
        t = result.get(self._time_attr, 0)
        score = self._score(result)
        if score is not None:
            self._latest[trial.trial_id] = score
            self._trials[trial.trial_id] = trial
        st = self._state[trial.trial_id]
        if t - st.last_perturb_t < self._interval or score is None:
            return CONTINUE
        st.last_perturb_t = t

        scores = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(scores)
        if n < 2:
            return CONTINUE
        k = max(1, int(math.ceil(n * self._quantile)))
        bottom = {tid for tid, _ in scores[:k]}
        top = [tid for tid, _ in scores[-k:]]
        if trial.trial_id in bottom:
            donor_id = top[int(self._rng.integers(0, len(top)))]
            donor = self._trials.get(donor_id)
            if donor is None or donor_id == trial.trial_id:
                return CONTINUE
            new_config = self._explore(dict(donor.config))
            trial.exploit_directive = {
                "donor": donor_id, "config": new_config}
            return PAUSE  # controller restarts from donor checkpoint
        return CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        for key, mutation in self._mutations.items():
            if self._rng.random() < self._resample_p or key not in config:
                if isinstance(mutation, Domain):
                    config[key] = mutation.sample(self._rng)
                elif isinstance(mutation, list):
                    config[key] = mutation[
                        int(self._rng.integers(0, len(mutation)))]
                elif callable(mutation):
                    config[key] = mutation()
            else:
                cur = config[key]
                if isinstance(cur, (int, float)):
                    factor = 1.2 if self._rng.random() < 0.5 else 0.8
                    config[key] = type(cur)(cur * factor)
        return config


class _HBBracket:
    """One successive-halving bracket: n0 starting trials, first rung
    budget r0, promoted survivors get eta× budget per rung."""

    def __init__(self, s: int, eta: float, max_t: int, s_max: int):
        self.eta = eta
        self.max_t = max_t
        self.n0 = int(math.ceil((s_max + 1) / (s + 1) * eta ** s))
        self.r = max(1, int(round(max_t * eta ** (-s))))
        self.members: Dict[str, Any] = {}       # trial_id -> Trial
        self.rung_scores: Dict[str, float] = {}  # at the CURRENT rung

    def has_room(self) -> bool:
        return len(self.members) < self.n0

    def live_ids(self) -> List[str]:
        return [tid for tid, t in self.members.items()
                if t.state not in ("TERMINATED", "ERROR")]


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference tune/schedulers/hyperband.py
    HyperBandScheduler, Li et al. 2018).

    Trials are assigned round-robin over a band of brackets s_max..0
    (aggressive early-stopping down to no early-stopping); within a
    bracket each trial PAUSEs at the rung boundary until the whole
    cohort arrives, then the top 1/eta continue with eta× budget and the
    rest stop. Pause/resume is driven through the controller's
    poll_paused hook (tune_controller.py _apply_unpause_decisions)."""

    def __init__(self, time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3):
        self._time_attr = time_attr
        self._max_t = int(max_t)
        self._eta = float(reduction_factor)
        if reduction_factor <= 1:
            raise ValueError(
                f"reduction_factor must be > 1, got {reduction_factor}")
        # Integer loop, not float log-ratio: log(243)/log(3) is
        # 4.9999…, which would truncate away the most aggressive
        # bracket for exact-power max_t values.
        s_max = 0
        while reduction_factor ** (s_max + 1) <= max_t:
            s_max += 1
        self._s_max = s_max
        self._brackets: List[_HBBracket] = []
        self._by_trial: Dict[str, _HBBracket] = {}

    def on_trial_add(self, trial):
        b = next((b for b in self._brackets if b.has_room()), None)
        if b is None:
            s = self._s_max - (len(self._brackets) % (self._s_max + 1))
            b = _HBBracket(s, self._eta, self._max_t, self._s_max)
            self._brackets.append(b)
        b.members[trial.trial_id] = trial
        self._by_trial[trial.trial_id] = b

    def on_trial_result(self, trial, result):
        t = result.get(self._time_attr)
        score = self._score(result)
        if t is None or score is None:
            return CONTINUE
        if t >= self._max_t:
            return STOP
        b = self._by_trial.get(trial.trial_id)
        if b is None:  # restored run predating the bracket assignment
            self.on_trial_add(trial)
            b = self._by_trial[trial.trial_id]
        if t >= b.r:
            b.rung_scores[trial.trial_id] = score
            return PAUSE  # wait for the cohort at this rung
        return CONTINUE

    def poll_paused(self) -> Dict[str, str]:
        """Rung barrier: once every live member of a bracket has banked
        a score for the current rung, promote the top 1/eta."""
        decisions: Dict[str, str] = {}
        for b in self._brackets:
            live = b.live_ids()
            if not live or not all(tid in b.rung_scores for tid in live):
                continue
            ranked = sorted(live, key=lambda tid: b.rung_scores[tid],
                            reverse=True)
            keep = max(1, int(math.ceil(len(live) / self._eta)))
            for tid in ranked[:keep]:
                decisions[tid] = CONTINUE
            for tid in ranked[keep:]:
                decisions[tid] = STOP
            # Survivors run to the next rung (trials hitting max_t stop
            # individually in on_trial_result, so no rung forms there).
            b.r = min(int(round(b.r * self._eta)), self._max_t)
            b.rung_scores = {}
        return decisions
