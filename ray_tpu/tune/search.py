"""Search spaces + search algorithms.

Counterpart of python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator).  Grid axes are expanded as a
cross-product repeated num_samples times; stochastic domains are sampled
per trial (reference basic_variant semantics).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# Domains (python/ray/tune/search/sample.py)
# ---------------------------------------------------------------------------


class Domain:
    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class GridSearch:
    values: Sequence[Any]


@dataclasses.dataclass
class Choice(Domain):
    values: Sequence[Any]

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]


@dataclasses.dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))


@dataclasses.dataclass
class QUniform(Domain):
    low: float
    high: float
    q: float

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return float(np.round(v / self.q) * self.q)


@dataclasses.dataclass
class LogUniform(Domain):
    low: float
    high: float
    base: float = 10.0

    def sample(self, rng):
        lo = math.log(self.low, self.base)
        hi = math.log(self.high, self.base)
        return float(self.base ** rng.uniform(lo, hi))


@dataclasses.dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))


@dataclasses.dataclass
class LogRandInt(Domain):
    low: int
    high: int
    base: float = 10.0

    def sample(self, rng):
        lo = math.log(self.low, self.base)
        hi = math.log(self.high, self.base)
        return int(round(self.base ** rng.uniform(lo, hi)))


@dataclasses.dataclass
class RandN(Domain):
    mean: float = 0.0
    sd: float = 1.0

    def sample(self, rng):
        return float(rng.normal(self.mean, self.sd))


@dataclasses.dataclass
class SampleFrom(Domain):
    fn: Callable[[Dict[str, Any]], Any]  # receives the partial config


# public constructors (mirror ray.tune module functions)
def grid_search(values) -> GridSearch:
    return GridSearch(list(values))


def choice(values) -> Choice:
    return Choice(list(values))


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def quniform(low, high, q) -> QUniform:
    return QUniform(low, high, q)


def loguniform(low, high, base: float = 10.0) -> LogUniform:
    return LogUniform(low, high, base)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def lograndint(low, high, base: float = 10.0) -> LogRandInt:
    return LogRandInt(low, high, base)


def randn(mean: float = 0.0, sd: float = 1.0) -> RandN:
    return RandN(mean, sd)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


# ---------------------------------------------------------------------------
# Variant generation
# ---------------------------------------------------------------------------


def _walk(space: Any, path=()):
    """Yield (path, leaf) for every leaf in a nested dict space."""
    if isinstance(space, dict):
        for k, v in space.items():
            yield from _walk(v, path + (k,))
    else:
        yield path, space


def _set_path(cfg: Dict, path, value):
    cur = cfg
    for key in path[:-1]:
        cur = cur.setdefault(key, {})
    cur[path[-1]] = value


class SearchAlgorithm:
    """Yields trial configs; informed of results for adaptive algorithms."""

    def set_space(self, space: Dict[str, Any], metric: Optional[str],
                  mode: str):
        raise NotImplementedError

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict],
                          error: bool = False):
        pass


class BasicVariantGenerator(SearchAlgorithm):
    """Grid cross-product × num_samples random draws
    (python/ray/tune/search/basic_variant.py)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._space: Dict[str, Any] = {}
        self._grid_axes: List = []
        self._grid_iter = None

    def set_space(self, space, metric, mode):
        self._space = space or {}
        self._grid_axes = [
            (path, leaf.values) for path, leaf in _walk(self._space)
            if isinstance(leaf, GridSearch)
        ]

    def grid_size(self) -> int:
        n = 1
        for _, values in self._grid_axes:
            n *= max(1, len(values))
        return n

    def _one(self, grid_assignment) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        deferred: List = []
        for path, leaf in _walk(self._space):
            if isinstance(leaf, GridSearch):
                continue
            if isinstance(leaf, SampleFrom):
                deferred.append((path, leaf))
            elif isinstance(leaf, Domain):
                _set_path(cfg, path, leaf.sample(self._rng))
            else:
                _set_path(cfg, path, leaf)
        for (path, values), v in grid_assignment:
            _set_path(cfg, path, v)
        for path, leaf in deferred:  # may reference sampled values
            _set_path(cfg, path, leaf.fn(cfg))
        return cfg

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        out = []
        for _ in range(n):
            if self._grid_axes:
                if self._grid_iter is None:
                    self._grid_iter = itertools.cycle(
                        itertools.product(*[
                            [((path, values), v) for v in values]
                            for path, values in self._grid_axes
                        ]))
                assignment = next(self._grid_iter)
            else:
                assignment = ()
            out.append(self._one(assignment))
        return out
