"""Search spaces + search algorithms.

Counterpart of python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator).  Grid axes are expanded as a
cross-product repeated num_samples times; stochastic domains are sampled
per trial (reference basic_variant semantics).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# Domains (python/ray/tune/search/sample.py)
# ---------------------------------------------------------------------------


class Domain:
    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class GridSearch:
    values: Sequence[Any]


@dataclasses.dataclass
class Choice(Domain):
    values: Sequence[Any]

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]


@dataclasses.dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))


@dataclasses.dataclass
class QUniform(Domain):
    low: float
    high: float
    q: float

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return float(np.round(v / self.q) * self.q)


@dataclasses.dataclass
class LogUniform(Domain):
    low: float
    high: float
    base: float = 10.0

    def sample(self, rng):
        lo = math.log(self.low, self.base)
        hi = math.log(self.high, self.base)
        return float(self.base ** rng.uniform(lo, hi))


@dataclasses.dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))


@dataclasses.dataclass
class LogRandInt(Domain):
    low: int
    high: int
    base: float = 10.0

    def sample(self, rng):
        lo = math.log(self.low, self.base)
        hi = math.log(self.high, self.base)
        return int(round(self.base ** rng.uniform(lo, hi)))


@dataclasses.dataclass
class RandN(Domain):
    mean: float = 0.0
    sd: float = 1.0

    def sample(self, rng):
        return float(rng.normal(self.mean, self.sd))


@dataclasses.dataclass
class SampleFrom(Domain):
    fn: Callable[[Dict[str, Any]], Any]  # receives the partial config


# public constructors (mirror ray.tune module functions)
def grid_search(values) -> GridSearch:
    return GridSearch(list(values))


def choice(values) -> Choice:
    return Choice(list(values))


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def quniform(low, high, q) -> QUniform:
    return QUniform(low, high, q)


def loguniform(low, high, base: float = 10.0) -> LogUniform:
    return LogUniform(low, high, base)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def lograndint(low, high, base: float = 10.0) -> LogRandInt:
    return LogRandInt(low, high, base)


def randn(mean: float = 0.0, sd: float = 1.0) -> RandN:
    return RandN(mean, sd)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


# ---------------------------------------------------------------------------
# Variant generation
# ---------------------------------------------------------------------------


def _walk(space: Any, path=()):
    """Yield (path, leaf) for every leaf in a nested dict space."""
    if isinstance(space, dict):
        for k, v in space.items():
            yield from _walk(v, path + (k,))
    else:
        yield path, space


def _set_path(cfg: Dict, path, value):
    cur = cfg
    for key in path[:-1]:
        cur = cur.setdefault(key, {})
    cur[path[-1]] = value


class SearchAlgorithm:
    """Yields trial configs; informed of results for adaptive algorithms."""

    def set_space(self, space: Dict[str, Any], metric: Optional[str],
                  mode: str):
        raise NotImplementedError

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict],
                          error: bool = False,
                          config: Optional[Dict[str, Any]] = None):
        pass


class BasicVariantGenerator(SearchAlgorithm):
    """Grid cross-product × num_samples random draws
    (python/ray/tune/search/basic_variant.py)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._space: Dict[str, Any] = {}
        self._grid_axes: List = []
        self._grid_iter = None

    def set_space(self, space, metric, mode):
        self._space = space or {}
        self._grid_axes = [
            (path, leaf.values) for path, leaf in _walk(self._space)
            if isinstance(leaf, GridSearch)
        ]

    def grid_size(self) -> int:
        n = 1
        for _, values in self._grid_axes:
            n *= max(1, len(values))
        return n

    def _one(self, grid_assignment) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        deferred: List = []
        for path, leaf in _walk(self._space):
            if isinstance(leaf, GridSearch):
                continue
            if isinstance(leaf, SampleFrom):
                deferred.append((path, leaf))
            elif isinstance(leaf, Domain):
                _set_path(cfg, path, leaf.sample(self._rng))
            else:
                _set_path(cfg, path, leaf)
        for (path, values), v in grid_assignment:
            _set_path(cfg, path, v)
        for path, leaf in deferred:  # may reference sampled values
            _set_path(cfg, path, leaf.fn(cfg))
        return cfg

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        out = []
        for _ in range(n):
            if self._grid_axes:
                if self._grid_iter is None:
                    self._grid_iter = itertools.cycle(
                        itertools.product(*[
                            [((path, values), v) for v in values]
                            for path, values in self._grid_axes
                        ]))
                assignment = next(self._grid_iter)
            else:
                assignment = ()
            out.append(self._one(assignment))
        return out


class TPESearcher(SearchAlgorithm):
    """Tree-structured Parzen Estimator search (Bergstra et al. 2011),
    pure numpy — the capability the reference gets from external
    libraries (tune/search/hyperopt, optuna's default sampler) without
    their dependencies.

    Per dimension, completed trials split into good (top ``gamma``
    quantile by objective) and bad; candidates sampled from the
    good-points KDE are scored by the density ratio l(x)/g(x) and the
    best candidate wins. Random sampling until ``n_initial`` results.
    Supported domains: Uniform, LogUniform, QUniform, RandInt,
    LogRandInt, RandN, Choice (categorical counts); grid_search and
    sample_from fall back to BasicVariant behavior per draw.
    """

    def __init__(self, n_initial: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = np.random.default_rng(seed)
        self._space: Dict[str, Any] = {}
        self._metric: Optional[str] = None
        self._mode = "max"
        self._dims: List = []        # (path, Domain)
        self._observations: List = []  # (config, score)
        self._fallback = BasicVariantGenerator(seed=seed)

    def set_space(self, space, metric, mode):
        self._space = space or {}
        self._metric = metric
        self._mode = mode
        self._fallback.set_space(space, metric, mode)
        self._dims = [
            (path, leaf) for path, leaf in _walk(self._space)
            if isinstance(leaf, Domain) and not isinstance(leaf, SampleFrom)
        ]

    def on_trial_complete(self, trial_id, result, error=False, config=None):
        score = self._score(result, error=error, config=config)
        if score is not None:
            self._record(config, score, result)

    def _score(self, result, *, error: bool, config) -> Optional[float]:
        """Normalized maximize-me objective, or None if unusable."""
        if error or not result or config is None or not self._metric:
            return None
        score = result.get(self._metric)
        if score is None:
            return None
        score = float(score)
        return -score if self._mode == "min" else score

    def _record(self, config, score: float, result) -> None:
        """Observation sink — subclasses re-bin (BOHB buckets by budget)."""
        self._observations.append((config, score))

    # -- per-dimension sampling -------------------------------------------
    @staticmethod
    def _get_path(cfg: Dict, path):
        cur = cfg
        for k in path:
            if not isinstance(cur, dict) or k not in cur:
                return None
            cur = cur[k]
        return cur

    def _to_unit(self, leaf, v) -> Optional[float]:
        """Map a domain value onto a continuous line for KDE."""
        try:
            if isinstance(leaf, (LogUniform, LogRandInt)):
                return float(np.log(float(v)))
            return float(v)
        except (TypeError, ValueError):
            return None

    def _from_line(self, leaf, x: float):
        if isinstance(leaf, LogUniform):
            return float(np.clip(np.exp(x), leaf.low, leaf.high))
        if isinstance(leaf, LogRandInt):
            return int(np.clip(round(np.exp(x)), leaf.low, leaf.high - 1))
        if isinstance(leaf, Uniform):
            return float(np.clip(x, leaf.low, leaf.high))
        if isinstance(leaf, QUniform):
            q = leaf.q
            return float(np.clip(round(x / q) * q, leaf.low, leaf.high))
        if isinstance(leaf, RandInt):
            return int(np.clip(round(x), leaf.low, leaf.high - 1))
        if isinstance(leaf, RandN):
            return float(x)
        return x

    @staticmethod
    def _kde_logpdf(points: np.ndarray, bw: float, xs: np.ndarray
                    ) -> np.ndarray:
        d = (xs[:, None] - points[None, :]) / bw
        # log-mean-exp of Gaussian kernels
        k = -0.5 * d * d - 0.5 * np.log(2 * np.pi) - np.log(bw)
        m = k.max(axis=1, keepdims=True)
        return (m[:, 0] + np.log(np.mean(np.exp(k - m), axis=1)))

    def _suggest_numeric(self, leaf, good: List[float], bad: List[float]):
        g = np.asarray(good, dtype=np.float64)
        b = np.asarray(bad, dtype=np.float64) if bad else g
        spread = max(g.std(), 1e-3) if len(g) > 1 else 1.0
        bw = max(spread * len(g) ** -0.2, 1e-3)
        cands = g[self._rng.integers(0, len(g), self.n_candidates)] + \
            self._rng.normal(0, bw, self.n_candidates)
        score = self._kde_logpdf(g, bw, cands) - \
            self._kde_logpdf(b, bw, cands)
        return float(cands[int(np.argmax(score))])

    def _suggest_choice(self, leaf, good_vals: List, bad_vals: List):
        values = list(leaf.values)
        idx = {self._key(v): i for i, v in enumerate(values)}
        g_counts = np.ones(len(values))
        b_counts = np.ones(len(values))
        for v in good_vals:
            i = idx.get(self._key(v))
            if i is not None:
                g_counts[i] += 1
        for v in bad_vals:
            i = idx.get(self._key(v))
            if i is not None:
                b_counts[i] += 1
        ratio = (g_counts / g_counts.sum()) / (b_counts / b_counts.sum())
        # Sample ∝ ratio (not argmax): concurrent suggestions stay
        # diverse and unlucky-early categories keep getting retried.
        p = ratio / ratio.sum()
        return values[int(self._rng.choice(len(values), p=p))]

    @staticmethod
    def _key(v):
        try:
            hash(v)
            return v
        except TypeError:
            return repr(v)

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        out = []
        for _ in range(n):
            if len(self._observations) < self.n_initial or not self._dims:
                out.extend(self._fallback.next_configs(1))
                continue
            ranked = sorted(self._observations, key=lambda cs: -cs[1])
            n_good = max(1, int(len(ranked) * self.gamma))
            good, bad = ranked[:n_good], ranked[n_good:]
            cfg = self._fallback.next_configs(1)[0]  # base (grids etc.)
            for path, leaf in self._dims:
                g_vals = [self._get_path(c, path) for c, _ in good]
                b_vals = [self._get_path(c, path) for c, _ in bad]
                g_vals = [v for v in g_vals if v is not None]
                b_vals = [v for v in b_vals if v is not None]
                if not g_vals:
                    continue
                if isinstance(leaf, Choice):
                    v = self._suggest_choice(leaf, g_vals, b_vals)
                else:
                    g_line = [self._to_unit(leaf, v) for v in g_vals]
                    b_line = [self._to_unit(leaf, v) for v in b_vals]
                    g_line = [v for v in g_line if v is not None]
                    b_line = [v for v in b_line if v is not None]
                    if not g_line:
                        continue
                    v = self._from_line(
                        leaf, self._suggest_numeric(leaf, g_line, b_line))
                _set_path(cfg, path, v)
            # Re-resolve sample_from leaves AGAINST the final values —
            # the fallback computed them from its own (now overwritten)
            # random draws.
            for path, leaf in _walk(self._space):
                if isinstance(leaf, SampleFrom):
                    _set_path(cfg, path, leaf.fn(cfg))
            out.append(cfg)
        return out


class ConcurrencyLimiter(SearchAlgorithm):
    """Caps in-flight suggestions from a wrapped searcher (reference
    tune/search/concurrency_limiter.py) — important for adaptive
    searchers, which degrade toward random when too many configs are
    suggested before any results return."""

    def __init__(self, searcher: SearchAlgorithm, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max(1, max_concurrent)
        self._inflight = 0

    def set_space(self, space, metric, mode):
        self.searcher.set_space(space, metric, mode)

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        allowed = min(n, self.max_concurrent - self._inflight)
        if allowed <= 0:
            return []
        configs = self.searcher.next_configs(allowed)
        self._inflight += len(configs)
        return configs

    def on_trial_complete(self, trial_id, result, error=False, config=None):
        self._inflight = max(0, self._inflight - 1)
        self.searcher.on_trial_complete(trial_id, result, error=error,
                                        config=config)


class BOHBSearcher(TPESearcher):
    """BOHB's model-based multi-fidelity proposals (Falkner et al. 2018)
    composed natively with HyperBandScheduler — the capability the
    reference gets from tune/search/bohb + schedulers/hb_bohb.py over
    the external hpbandster dependency.

    Observations are keyed by the budget a trial REACHED (its final
    ``time_attr``): under HyperBand, every rung's stopped cohort
    completes at that rung's budget, so completed trials alone span all
    fidelities — no mid-trial searcher hook needed.  Proposals condition
    on the LARGEST budget that has enough observations (the paper's
    model-selection rule: models on high budgets are most informative,
    low budgets fill in while they warm up), falling back to random
    sampling before any budget qualifies.

    Use paired with the rung scheduler::

        TuneConfig(search_alg=BOHBSearcher(),
                   scheduler=HyperBandScheduler(max_t=81))
    """

    def __init__(self, n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None,
                 time_attr: str = "training_iteration"):
        super().__init__(n_initial=n_initial, gamma=gamma,
                         n_candidates=n_candidates, seed=seed)
        self._time_attr = time_attr
        self._obs_by_budget: Dict[float, List] = {}

    def _record(self, config, score: float, result) -> None:
        raw = result.get(self._time_attr)
        budget = self._budget_bin(1.0 if raw is None else float(raw))
        self._obs_by_budget.setdefault(budget, []).append((config, score))

    @staticmethod
    def _budget_bin(budget: float) -> float:
        """Integral budgets (training_iteration rungs) key exactly;
        continuous attrs (time_total_s) coalesce to 2 significant
        figures — otherwise every completion lands in a singleton
        bucket and no budget ever accumulates a model."""
        if budget == int(budget):
            return budget
        if budget <= 0:
            return budget
        exp = math.floor(math.log10(abs(budget)))
        q = 10.0 ** (exp - 1)
        return round(budget / q) * q

    def _model_budget(self) -> Optional[float]:
        """Largest budget with enough observations to fit the KDE split."""
        need = max(self.n_initial, len(self._dims) + 2)
        qualified = [b for b, obs in self._obs_by_budget.items()
                     if len(obs) >= need]
        return max(qualified) if qualified else None

    def next_configs(self, n: int) -> List[Dict[str, Any]]:
        budget = self._model_budget()
        # TPESearcher.next_configs proposes from self._observations;
        # point it at the chosen fidelity's observation set.
        self._observations = (
            self._obs_by_budget.get(budget, []) if budget is not None
            else [])
        return super().next_configs(n)
