"""PB2: Population Based Bandits (Parker-Holder et al., NeurIPS 2020).

Counterpart of python/ray/tune/schedulers/pb2.py (507 LoC wrapping GPy):
PBT's exploit step with the random perturbation replaced by a GP-bandit
suggestion — a Gaussian process is fit on (time, hyperparameters) →
reward *change* observations from the whole population, and the new
hyperparameters for the exploiting trial maximize UCB over the bounded
search box.  Native numpy GP (RBF kernel + jittered Cholesky), no GPy
dependency.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.schedulers import (
    CONTINUE,
    PAUSE,
    PopulationBasedTraining,
)


def _rbf(a: np.ndarray, b: np.ndarray, ls: float) -> np.ndarray:
    d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    return np.exp(-0.5 * d2 / (ls * ls))


class _GP:
    """Minimal GP regression: RBF kernel, fixed unit signal variance,
    median-heuristic lengthscale, jittered Cholesky solve."""

    def __init__(self, x: np.ndarray, y: np.ndarray, noise: float = 1e-2):
        self.x = x
        mu, sd = y.mean(), max(y.std(), 1e-8)
        self.y_mu, self.y_sd = mu, sd
        self.y = (y - mu) / sd
        if len(x) > 1:
            d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
            med = np.median(d2[d2 > 0]) if (d2 > 0).any() else 1.0
            self.ls = math.sqrt(max(med, 1e-6))
        else:
            self.ls = 1.0
        k = _rbf(x, x, self.ls) + noise * np.eye(len(x))
        self.chol = np.linalg.cholesky(k + 1e-8 * np.eye(len(x)))
        self.alpha = np.linalg.solve(
            self.chol.T, np.linalg.solve(self.chol, self.y))

    def predict(self, xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        ks = _rbf(xs, self.x, self.ls)
        mu = ks @ self.alpha
        v = np.linalg.solve(self.chol, ks.T)
        var = np.clip(1.0 - (v * v).sum(0), 1e-12, None)
        return mu * self.y_sd + self.y_mu, np.sqrt(var) * self.y_sd


class PB2(PopulationBasedTraining):
    """PBT with a GP-bandit explore step over continuous bounds.

    hyperparam_bounds: {key: (low, high)} continuous box; categorical
    keys can still be mutated PBT-style via hyperparam_mutations.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[
                     Dict[str, Tuple[float, float]]] = None,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 2.0,
                 n_candidates: int = 256,
                 log_scale_auto: bool = True,
                 seed: Optional[int] = None):
        super().__init__(
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations=hyperparam_mutations,
            quantile_fraction=quantile_fraction,
            seed=seed)
        self._bounds = {k: (float(lo), float(hi))
                        for k, (lo, hi) in (hyperparam_bounds or {}).items()}
        self._kappa = ucb_kappa
        self._n_candidates = n_candidates
        # Auto log-scaling for bounds spanning >=2 decades (learning
        # rates etc.) — PB2's GP operates in a warped unit box.
        self._log = {
            k: (log_scale_auto and lo > 0 and hi / max(lo, 1e-300) >= 100)
            for k, (lo, hi) in self._bounds.items()}
        # Per-trial observation history: time -> (score, config snapshot)
        self._history: Dict[str, List[Tuple[float, float, Dict]]] = \
            defaultdict(list)

    # -- data collection ----------------------------------------------------
    def on_trial_result(self, trial, result):
        score = self._score(result)
        t = result.get(self._time_attr, 0)
        if score is not None:
            self._history[trial.trial_id].append(
                (float(t), score, {k: trial.config.get(k)
                                   for k in self._bounds}))
        return super().on_trial_result(trial, result)

    # -- warping ------------------------------------------------------------
    def _to_unit(self, key: str, v: float) -> float:
        lo, hi = self._bounds[key]
        if self._log[key]:
            lo_, hi_, v_ = math.log(lo), math.log(hi), math.log(
                max(float(v), 1e-300))
            return (v_ - lo_) / max(hi_ - lo_, 1e-12)
        return (float(v) - lo) / max(hi - lo, 1e-12)

    def _from_unit(self, key: str, u: float) -> float:
        lo, hi = self._bounds[key]
        u = min(max(u, 0.0), 1.0)
        if self._log[key]:
            return float(math.exp(math.log(lo)
                                  + u * (math.log(hi) - math.log(lo))))
        return float(lo + u * (hi - lo))

    # -- GP-bandit explore (overrides PBT's random perturbation) ------------
    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        keys = list(self._bounds)
        data_x, data_y = [], []
        tmax = 1.0
        for obs in self._history.values():
            for (t, _, _) in obs:
                tmax = max(tmax, t)
        for obs in self._history.values():
            for i in range(1, len(obs)):
                t0, s0, _ = obs[i - 1]
                t1, s1, cfg = obs[i]
                xs = [t1 / tmax] + [
                    self._to_unit(k, cfg.get(k, self._bounds[k][0]))
                    for k in keys]
                data_x.append(xs)
                data_y.append(s1 - s0)  # reward CHANGE — PB2's target
        if len(data_y) >= 3:
            gp = _GP(np.asarray(data_x), np.asarray(data_y))
            cand_u = self._rng.uniform(
                0, 1, size=(self._n_candidates, len(keys)))
            t_col = np.full((self._n_candidates, 1), 1.0)  # next window
            mu, sd = gp.predict(np.concatenate([t_col, cand_u], axis=1))
            best = cand_u[int(np.argmax(mu + self._kappa * sd))]
            for k, u in zip(keys, best):
                new = self._from_unit(k, float(u))
                cur = config.get(k)
                config[k] = type(cur)(new) if isinstance(cur, int) else new
        else:
            # Too little signal for a GP: uniform draw inside the box
            # (the paper's cold-start behavior).
            for k in keys:
                new = self._from_unit(k, float(self._rng.uniform()))
                cur = config.get(k)
                config[k] = type(cur)(new) if isinstance(cur, int) else new
        # Non-bounded (categorical) keys keep PBT-style mutation.
        if self._mutations:
            config = super()._explore(config)
        return config
