"""Tuner: the user-facing tuning entry point.

Counterpart of python/ray/tune/tuner.py (Tuner.fit :44/:344 →
TunerInternal → TuneController) and result_grid.py ResultGrid.  Also
wraps DataParallelTrainer instances as trainables the way the reference
wraps trainers in a TrainTrainable (base_trainer.py:724).
"""

from __future__ import annotations

import copy
import dataclasses
import os
from typing import Any, Dict, List, Optional

from ray_tpu.train.config import RunConfig
from ray_tpu.train.trainer import DataParallelTrainer, Result
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, SearchAlgorithm
from ray_tpu.tune.tune_controller import (
    TuneController,
    trials_to_results,
)


@dataclasses.dataclass
class TuneConfig:
    """python/ray/tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    search_alg: Optional[SearchAlgorithm] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None


class ResultGrid:
    """python/ray/tune/result_grid.py."""

    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (none set in TuneConfig)")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics or {} for r in self._results])


def _trainer_to_trainable(trainer: DataParallelTrainer):
    """Run a trainer inside a trial, merging the trial config into
    train_loop_config and re-reporting its results
    (reference TrainTrainable, base_trainer.py:724)."""

    def trainable(config: Dict[str, Any]):
        from ray_tpu.tune.trainable import report

        t = copy.copy(trainer)
        t.train_loop_config = {**(trainer.train_loop_config or {}), **config}
        # Each trial gets its own run dir under the trial sandbox.
        from ray_tpu.tune.trainable import get_trial_dir, get_trial_id

        t.run_config = copy.copy(trainer.run_config)
        t.run_config.storage_path = get_trial_dir() or None
        t.run_config.name = "train"
        result = t.fit()
        for entry in result.metrics_history:
            report(dict(entry))
        if not result.metrics_history and result.metrics:
            report(dict(result.metrics))

    return trainable


class Tuner:
    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self._user_trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self.resources_per_trial = resources_per_trial
        self._restore_path: Optional[str] = None

    @classmethod
    def restore(cls, path: str, trainable, *,
                param_space: Optional[Dict] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional["RunConfig"] = None,
                resources_per_trial: Optional[Dict[str, float]] = None
                ) -> "Tuner":
        """Resume an interrupted experiment from its run directory
        (reference: Tuner.restore / tune/execution/experiment_state.py).
        Finished trials keep their results; unfinished ones re-run from
        their last checkpoint with their original configs; samples the
        crashed run never created are drawn fresh (pass the original
        param_space for that). Pass the original run_config to keep stop
        criteria / failure limits — the state file does not record them."""
        if not os.path.exists(os.path.join(path, "experiment_state.json")):
            raise FileNotFoundError(
                f"no experiment_state.json under {path!r}")
        t = cls(trainable, param_space=param_space,
                tune_config=tune_config, run_config=run_config,
                resources_per_trial=resources_per_trial)
        t._restore_path = path
        return t

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        trainable = self._user_trainable
        if isinstance(trainable, DataParallelTrainer):
            trainable = _trainer_to_trainable(trainable)

        search = tc.search_alg or BasicVariantGenerator(seed=tc.seed)
        search.set_space(self.param_space, tc.metric, tc.mode)
        scheduler = tc.scheduler or FIFOScheduler()

        num_samples = tc.num_samples
        # Unwrap ConcurrencyLimiter-style wrappers for grid accounting.
        grid_owner = search
        while hasattr(grid_owner, "searcher"):
            grid_owner = grid_owner.searcher
        if hasattr(grid_owner, "grid_size"):
            # grid axes multiply the sample count (reference semantics:
            # num_samples repeats of the full grid).
            num_samples = tc.num_samples * grid_owner.grid_size()

        run_dir = self._restore_path or os.path.join(
            self.run_config.storage_path or
            os.path.expanduser("~/ray_tpu_results"),
            self.run_config.name or "tune_run")
        stop = getattr(self.run_config, "stop", None)
        controller = TuneController(
            trainable,
            search_alg=search,
            scheduler=scheduler,
            num_samples=num_samples,
            metric=tc.metric,
            mode=tc.mode,
            max_concurrent=tc.max_concurrent_trials,
            run_dir=run_dir,
            stop=stop,
            max_failures=self.run_config.failure_config.max_failures,
            resources_per_trial=self.resources_per_trial,
            callbacks=getattr(self.run_config, "callbacks", None),
        )
        if self._restore_path:
            self._seed_restored_trials(controller)
        trials = controller.run()
        return ResultGrid(trials_to_results(trials), tc.metric, tc.mode)

    def _seed_restored_trials(self, controller: TuneController) -> None:
        """Rebuild trial state from experiment_state.json: TERMINATED
        trials keep results; everything else re-runs (from its last
        checkpoint when one exists) with its original config; samples
        never created before the crash are drawn lazily as usual."""
        import json
        import pickle

        from ray_tpu.tune.tune_controller import TERMINATED, Trial

        with open(os.path.join(self._restore_path,
                               "experiment_state.json")) as f:
            saved = json.load(f)
        # Lossless configs (the JSON state stringifies non-JSON values).
        exact_configs = {}
        sidecar = os.path.join(self._restore_path, ".trial_configs.pkl")
        if os.path.exists(sidecar):
            try:
                with open(sidecar, "rb") as f:
                    exact_configs = pickle.load(f)
            except Exception:
                exact_configs = {}
        trials = []
        for rec in saved["trials"]:
            cfg = exact_configs.get(rec["trial_id"], rec["config"])
            if not isinstance(cfg, dict):
                raise ValueError(
                    f"trial {rec['trial_id']} config was not recoverable "
                    f"({cfg!r}); the run predates the config sidecar")
            t = Trial(
                trial_id=rec["trial_id"],
                config=cfg,
                trial_dir=os.path.join(self._restore_path,
                                       rec["trial_id"]))
            t.last_checkpoint = rec.get("last_checkpoint")
            if rec["state"] == TERMINATED and not rec.get("error"):
                t.state = TERMINATED
                t.last_result = rec.get("last_result")
                if t.last_result:
                    t.metrics_history.append(t.last_result)
                    # Replay into the scheduler so ASHA/median cutoffs
                    # see the completed population, not an empty rung.
                    try:
                        controller._scheduler.on_trial_result(
                            t, t.last_result)
                        controller._scheduler.on_trial_complete(
                            t, t.last_result)
                    except Exception:
                        pass
            trials.append(t)
        controller.trials = trials
        controller._num_samples = max(
            int(saved.get("num_samples", len(trials))), len(trials))
        # Fast-forward the fresh searcher past the draws the original run
        # already made: finite/grid searchers must resume at the next
        # unseen point, not re-cycle duplicates from the start (random /
        # TPE searchers just discard the replayed draws). Unwrap any
        # ConcurrencyLimiter — its in-flight cap would truncate the
        # replay AND leave _inflight inflated with no completions coming.
        if trials:
            try:
                search = controller._search
                search = getattr(search, "searcher", search)
                search.next_configs(len(trials))
            except Exception:
                pass
