"""Tuner: the user-facing tuning entry point.

Counterpart of python/ray/tune/tuner.py (Tuner.fit :44/:344 →
TunerInternal → TuneController) and result_grid.py ResultGrid.  Also
wraps DataParallelTrainer instances as trainables the way the reference
wraps trainers in a TrainTrainable (base_trainer.py:724).
"""

from __future__ import annotations

import copy
import dataclasses
import os
from typing import Any, Dict, List, Optional

from ray_tpu.train.config import RunConfig
from ray_tpu.train.trainer import DataParallelTrainer, Result
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, SearchAlgorithm
from ray_tpu.tune.tune_controller import (
    TuneController,
    trials_to_results,
)


@dataclasses.dataclass
class TuneConfig:
    """python/ray/tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    search_alg: Optional[SearchAlgorithm] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None


class ResultGrid:
    """python/ray/tune/result_grid.py."""

    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (none set in TuneConfig)")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics or {} for r in self._results])


def _trainer_to_trainable(trainer: DataParallelTrainer):
    """Run a trainer inside a trial, merging the trial config into
    train_loop_config and re-reporting its results
    (reference TrainTrainable, base_trainer.py:724)."""

    def trainable(config: Dict[str, Any]):
        from ray_tpu.tune.trainable import report

        t = copy.copy(trainer)
        t.train_loop_config = {**(trainer.train_loop_config or {}), **config}
        # Each trial gets its own run dir under the trial sandbox.
        from ray_tpu.tune.trainable import get_trial_dir, get_trial_id

        t.run_config = copy.copy(trainer.run_config)
        t.run_config.storage_path = get_trial_dir() or None
        t.run_config.name = "train"
        result = t.fit()
        for entry in result.metrics_history:
            report(dict(entry))
        if not result.metrics_history and result.metrics:
            report(dict(result.metrics))

    return trainable


class Tuner:
    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self._user_trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self.resources_per_trial = resources_per_trial

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        trainable = self._user_trainable
        if isinstance(trainable, DataParallelTrainer):
            trainable = _trainer_to_trainable(trainable)

        search = tc.search_alg or BasicVariantGenerator(seed=tc.seed)
        search.set_space(self.param_space, tc.metric, tc.mode)
        scheduler = tc.scheduler or FIFOScheduler()

        num_samples = tc.num_samples
        # Unwrap ConcurrencyLimiter-style wrappers for grid accounting.
        grid_owner = search
        while hasattr(grid_owner, "searcher"):
            grid_owner = grid_owner.searcher
        if hasattr(grid_owner, "grid_size"):
            # grid axes multiply the sample count (reference semantics:
            # num_samples repeats of the full grid).
            num_samples = tc.num_samples * grid_owner.grid_size()

        run_dir = os.path.join(
            self.run_config.storage_path or
            os.path.expanduser("~/ray_tpu_results"),
            self.run_config.name or "tune_run")
        stop = getattr(self.run_config, "stop", None)
        controller = TuneController(
            trainable,
            search_alg=search,
            scheduler=scheduler,
            num_samples=num_samples,
            metric=tc.metric,
            mode=tc.mode,
            max_concurrent=tc.max_concurrent_trials,
            run_dir=run_dir,
            stop=stop,
            max_failures=self.run_config.failure_config.max_failures,
            resources_per_trial=self.resources_per_trial,
        )
        trials = controller.run()
        return ResultGrid(trials_to_results(trials), tc.metric, tc.mode)
