"""Trainables + the trial-runner actor.

Counterpart of python/ray/tune/trainable/ (Trainable ABC, function
trainables run in an actor with a result queue).  Both styles:

  - function trainable: ``def train(config): ... tune.report(metrics)`` —
    runs in a daemon thread inside the trial actor; ``tune.report`` blocks
    on a maxsize-1 queue (lockstep with the controller, same flow as the
    train session).
  - class Trainable: subclass with setup/step/save_checkpoint/
    load_checkpoint; the actor calls step() on demand.
"""

from __future__ import annotations

import os
import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Inside a function trainable: hand metrics (and optionally a
    checkpoint) to the controller (reference ray.tune.report)."""
    s = getattr(_local, "session", None)
    if s is None:
        raise RuntimeError("tune.report() called outside a tune trial")
    s.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = getattr(_local, "session", None)
    if s is None:
        raise RuntimeError("tune.get_checkpoint() called outside a trial")
    return s.loaded_checkpoint


def get_trial_id() -> str:
    s = getattr(_local, "session", None)
    return s.trial_id if s is not None else ""


def get_trial_dir() -> str:
    s = getattr(_local, "session", None)
    return s.trial_dir if s is not None else ""


class _TuneSession:
    def __init__(self, trial_id: str, trial_dir: str,
                 loaded_checkpoint: Optional[Checkpoint]):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.loaded_checkpoint = loaded_checkpoint
        self.result_queue: "queue.Queue" = queue.Queue(maxsize=1)
        self.finished = threading.Event()

    def report(self, metrics, checkpoint):
        self.result_queue.put(
            {"metrics": dict(metrics), "checkpoint": checkpoint})


class Trainable:
    """Class trainable API (python/ray/tune/trainable/trainable.py):
    setup(config) → repeated step() → save/load checkpoints."""

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass


class TrialRunner:
    """Hosts one trial (function or class trainable).

    The controller drives it with next_result() pulls; for class
    trainables each pull advances one step() (the reference's
    train-result cadence)."""

    def __init__(self, trainable, config: Dict[str, Any], trial_id: str,
                 trial_dir: str, checkpoint_path: Optional[str] = None):
        os.makedirs(trial_dir, exist_ok=True)
        self._trainable = trainable
        self._config = config
        self._trial_id = trial_id
        self._trial_dir = trial_dir
        self._ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        self._error: Optional[str] = None
        self._iteration = 0
        self._ckpt_counter = 0

        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self._mode = "class"
            self._instance = trainable()
            try:
                self._instance.setup(dict(config))
                if self._ckpt is not None:
                    self._instance.load_checkpoint(self._ckpt.as_directory())
            except BaseException:
                self._error = traceback.format_exc()
        else:
            self._mode = "function"
            self._session = _TuneSession(trial_id, trial_dir, self._ckpt)
            self._thread = threading.Thread(
                target=self._run_function, daemon=True)
            self._thread.start()

    # -- function-mode loop -------------------------------------------------
    def _run_function(self):
        _local.session = self._session
        try:
            out = self._trainable(dict(self._config))
            if isinstance(out, dict):
                self._session.result_queue.put(
                    {"metrics": out, "checkpoint": None})
        except BaseException:
            self._error = traceback.format_exc()
        finally:
            self._session.finished.set()

    # -- controller surface -------------------------------------------------
    def next_result(self, timeout: float = 1.0) -> Optional[Dict[str, Any]]:
        if self._mode == "class":
            return self._class_step()
        if self._error is not None:
            return {"error": True, "traceback": self._error}
        try:
            item = self._session.result_queue.get(timeout=timeout)
        except queue.Empty:
            if self._error is not None:
                return {"error": True, "traceback": self._error}
            if self._session.finished.is_set() \
                    and self._session.result_queue.empty():
                return {"finished": True}
            return None
        self._iteration += 1
        return self._package(item)

    def _class_step(self) -> Dict[str, Any]:
        if self._error is not None:
            return {"error": True, "traceback": self._error}
        try:
            metrics = self._instance.step()
        except StopIteration:
            return {"finished": True}
        except BaseException:
            return {"error": True, "traceback": traceback.format_exc()}
        self._iteration += 1
        ckpt = None
        return self._package({"metrics": metrics or {}, "checkpoint": ckpt})

    def _package(self, item: Dict[str, Any]) -> Dict[str, Any]:
        metrics = dict(item.get("metrics") or {})
        metrics.setdefault("training_iteration", self._iteration)
        out = {"metrics": metrics}
        ckpt = item.get("checkpoint")
        if ckpt is not None:
            out["checkpoint_path"] = self._persist(ckpt)
        return out

    def _persist(self, ckpt: Checkpoint) -> str:
        self._ckpt_counter += 1
        dest = os.path.join(
            self._trial_dir, f"checkpoint_{self._ckpt_counter:06d}")
        ckpt.to_directory(dest)
        return dest

    def save(self) -> Optional[str]:
        """Checkpoint a class trainable on demand (scheduler pause/PBT)."""
        if self._mode != "class":
            return None
        self._ckpt_counter += 1
        dest = os.path.join(
            self._trial_dir, f"checkpoint_{self._ckpt_counter:06d}")
        os.makedirs(dest, exist_ok=True)
        self._instance.save_checkpoint(dest)
        return dest

    def stop(self) -> bool:
        if self._mode == "class":
            try:
                self._instance.cleanup()
            except BaseException:
                pass
        return True
