"""Pluggable control-plane KV storage.

Counterpart of the reference's GCS StoreClient layer (SURVEY.md §2.1 N6:
store_client.h iface, InMemoryStoreClient, RedisStoreClient — the thing
that lets a restarted GCS recover cluster metadata). Two backends:

  - InMemoryStoreClient: a dict (the default, as in the reference).
  - FileBackedStoreClient: dict + append-only journal on disk; a new
    instance pointed at the same path replays the journal, so the
    cluster KV (runtime-env packages, named functions, user KV, job
    records) survives a head restart. Journal compaction happens on
    open when the log has accumulated enough dead weight.

Both expose MutableMapping, so the control server's dict-style usage
(`self.kv[k] = v`, `.get`, `del`, iteration) works unchanged.
"""

from __future__ import annotations

import os
import pickle
import struct
from collections.abc import MutableMapping
from typing import Any, Dict, Iterator

_LEN = struct.Struct("<I")
# Journal record: (key, value) = put; (key, None-sentinel) = delete.
_DELETE = ("__store_client_delete__",)


class InMemoryStoreClient(MutableMapping):
    def __init__(self):
        self._d: Dict[str, Any] = {}

    def __getitem__(self, k):
        return self._d[k]

    def __setitem__(self, k, v):
        self._d[k] = v

    def __delitem__(self, k):
        del self._d[k]

    def __iter__(self) -> Iterator[str]:
        return iter(self._d)

    def __len__(self) -> int:
        return len(self._d)

    def close(self):
        pass


class FileBackedStoreClient(MutableMapping):
    """Append-only journal + in-memory view (the Redis role, fileless)."""

    # Compact when the journal holds this many times more records than
    # live keys (dead puts/deletes dominate).
    _COMPACT_RATIO = 4

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._d: Dict[str, Any] = {}
        self._records = 0
        self._replay()
        if self._records > max(16, len(self._d) * self._COMPACT_RATIO):
            self._compact()
        self._f = open(path, "ab")

    def _replay(self):
        if not os.path.exists(self.path):
            return
        valid_end = 0
        with open(self.path, "rb") as f:
            while True:
                header = f.read(_LEN.size)
                if len(header) < _LEN.size:
                    break
                (n,) = _LEN.unpack(header)
                blob = f.read(n)
                if len(blob) < n:
                    break  # torn tail write (crash mid-append)
                try:
                    key, value = pickle.loads(blob)
                except Exception:
                    break
                valid_end = f.tell()
                # Type-check before comparing: arbitrary values (numpy
                # arrays) don't support bool(==); and only the exact
                # sentinel tuple is a delete.
                if isinstance(value, tuple) and value == _DELETE:
                    self._d.pop(key, None)
                else:
                    self._d[key] = value
                self._records += 1
        # Truncate any torn tail: appending AFTER garbage would make
        # every post-crash record unreachable on the next replay.
        if os.path.getsize(self.path) > valid_end:
            with open(self.path, "r+b") as f:
                f.truncate(valid_end)

    def _append(self, key: str, value: Any):
        blob = pickle.dumps((key, value), protocol=5)
        try:
            self._f.write(_LEN.pack(len(blob)) + blob)
            self._f.flush()
        except ValueError:
            return  # closed during shutdown; in-memory view stays right
        self._records += 1
        # Inline compaction: overwrite-heavy keys (metrics snapshots)
        # would otherwise grow the journal without bound until restart.
        if self._records > max(64, len(self._d) * self._COMPACT_RATIO):
            self._f.close()
            self._compact()
            self._f = open(self.path, "ab")

    def _compact(self):
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for k, v in self._d.items():
                blob = pickle.dumps((k, v), protocol=5)
                f.write(_LEN.pack(len(blob)) + blob)
        os.replace(tmp, self.path)
        self._records = len(self._d)

    # -- MutableMapping ----------------------------------------------------
    def __getitem__(self, k):
        return self._d[k]

    def __setitem__(self, k, v):
        if isinstance(v, tuple) and v == _DELETE:
            raise ValueError(
                "value collides with the journal's delete sentinel")
        self._d[k] = v
        self._append(k, v)

    def __delitem__(self, k):
        del self._d[k]
        self._append(k, _DELETE)

    def __iter__(self) -> Iterator[str]:
        return iter(self._d)

    def __len__(self) -> int:
        return len(self._d)

    def close(self):
        try:
            self._f.close()
        except Exception:
            pass


def make_store_client(path: str = ""):
    """'' → in-memory (default); a path → file-backed journal."""
    return FileBackedStoreClient(path) if path else InMemoryStoreClient()


def peek_journal_key(path: str, key: str):
    """Read one key from a journal without keeping it open (used by a
    restarting head to adopt the previous session id before the control
    server re-opens the store)."""
    if not path or not os.path.exists(path):
        return None
    store = FileBackedStoreClient(path)
    try:
        return store.get(key)
    finally:
        store.close()
