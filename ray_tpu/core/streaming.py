"""Streaming generator tasks: results flow to the caller as they are
yielded.

Counterpart of the reference's streaming-generator returns
(src/ray/protobuf/core_worker.proto streaming-generator RPCs,
python/ray/_raylet.pyx :1324/:1367 — `num_returns="streaming"` yields an
ObjectRefGenerator). Design here leans on the owner-directory instead of
a dedicated RPC pair: item object ids are DERIVED deterministically from
the task id + index, so the caller can subscribe to item i before it
exists and the worker never round-trips to hand out ids; a derived
end-of-stream object carries the final item count.

    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i

    for ref in gen.remote(5):      # ObjectRefGenerator
        value = ray_tpu.get(ref)
"""

from __future__ import annotations

import hashlib
from concurrent.futures import FIRST_COMPLETED, wait
from typing import Optional

from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef

STREAMING = "streaming"
_EOS_INDEX = -1


def stream_item_id(task_id: TaskID, index: int) -> ObjectID:
    """Deterministic object id for the index-th yielded item (the
    reference packs an index into the return id; we hash, since our ids
    carry no structure)."""
    digest = hashlib.sha1(
        task_id.binary() + index.to_bytes(8, "little", signed=True)
    ).digest()
    return ObjectID(digest[:14])


def stream_eos_id(task_id: TaskID) -> ObjectID:
    return stream_item_id(task_id, _EOS_INDEX)


class ObjectRefGenerator:
    """Iterator over a streaming task's item refs, in yield order.

    Each __next__ blocks until item i exists OR the stream is known to
    have ended before i (StopIteration). A failed generator stores the
    error into its final item slot, so iterating still surfaces it on
    get() — same contract as the reference.
    """

    def __init__(self, task_id: TaskID, runtime=None):
        self._task_id = task_id
        self._rt = runtime
        self._i = 0
        self._count: Optional[int] = None

    @property
    def task_id(self) -> TaskID:
        return self._task_id

    def _runtime(self):
        if self._rt is None:
            from ray_tpu.core.runtime import get_runtime

            self._rt = get_runtime()
        return self._rt

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        if self._count is not None and self._i >= self._count:
            raise StopIteration
        core = self._runtime().core
        item_hex = stream_item_id(self._task_id, self._i).hex()
        if self._count is None:
            item_fut = core.object_future(item_hex)
            eos_fut = core.object_future(
                stream_eos_id(self._task_id).hex())
            while not item_fut.done():
                wait([item_fut, eos_fut], return_when=FIRST_COMPLETED)
                # Both may resolve in the same wake (a crashed worker's
                # error EOS lands right behind its last item): the item,
                # when present, wins — the EOS is only consulted for
                # indexes past the stream's end.
                if item_fut.done():
                    break
                if eos_fut.done():
                    # Stream ended; resolve the count exactly once. A
                    # failed task stores an ERROR eos, which raises here
                    # — retire the speculative item probe either way.
                    eos_hex = stream_eos_id(self._task_id).hex()
                    try:
                        self._count = core._load_object(
                            eos_hex, eos_fut.result())
                    except BaseException:
                        core.forget_object(item_hex)
                        raise
                    try:
                        core.client.send({"op": "decref", "obj": eos_hex})
                    except Exception:
                        pass
                    if self._i >= self._count:
                        # The probe subscribed item[count], which will
                        # never exist — retire the speculative entry so
                        # heavy stream consumers don't leak directory
                        # entries/futures.
                        core.forget_object(item_hex)
                        raise StopIteration
                    # Items are stored BEFORE eos, so item i exists: the
                    # ref is valid even if its push hasn't landed yet
                    # (get() waits on the same future). No more spinning.
                    break
        # else: count known and i < count — the item already exists.
        self._i += 1
        return ObjectRef(ObjectID.from_hex(item_hex))

    def disown(self):
        """The caller takes over server-side stream cleanup (serve's
        proxy consumes by task id and sends its own free_stream with
        accurate consumed/count state): suppress __del__'s own free so
        a stale duplicate never parks on the head."""
        self._disowned = True

    def __del__(self):
        # Free unconsumed items server-side (they were stored with one
        # owner ref that only __next__'s ObjectRefs would release).
        # If the stream is still RUNNING, the head parks this free and
        # applies it when the EOS object lands (gcs.py _op_free_stream /
        # _store_object_locked) — mid-stream drops clean up too.
        if getattr(self, "_disowned", False):
            return
        try:
            rt = self._rt
            if rt is None or not getattr(rt, "is_initialized", False):
                return
            rt.core.client.send({
                "op": "free_stream",
                "task": self._task_id.hex(),
                "from_index": self._i,
                "eos_consumed": self._count is not None,
                # When this consumer already read the EOS (and its
                # decref may have DELETED it head-side), the head can't
                # learn the item count from the EOS anymore — ship it.
                "count": self._count,
            })
        except Exception:
            pass

    def __reduce__(self):
        # Generators are owner-local handles (like the reference's);
        # pass the yielded refs to other tasks instead.
        raise TypeError(
            "ObjectRefGenerator cannot be serialized; iterate it and "
            "pass the ObjectRefs")
