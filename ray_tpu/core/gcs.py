"""Control server: object directory, actor registry, KV, scheduler, worker pool.

This is the single-node fusion of the reference's GCS server
(src/ray/gcs/gcs_server/gcs_server.cc — actor/node/KV/pubsub managers) and
raylet (src/ray/raylet/node_manager.cc — ClusterTaskManager / LocalTaskManager
/ WorkerPool).  It runs as threads inside the head process and speaks the
rpc.py framed protocol to driver and worker processes.

Design deviations from the reference, deliberate for the TPU-first rebuild:
  - Small objects live in the directory itself rather than in per-owner
    memory stores; on a single node the directory IS the owner's metadata
    table.  Multi-node ownership (owner-resident values + location lookups,
    reference reference_count.h / ownership_based_object_directory.cc) is
    layered on in the multi-host control plane.
  - Scheduling is event-driven FIFO + resource fit over one node; the
    hybrid pack/spread policy slot is where multi-node placement goes.
  - TPU chips are scheduled like GPUs in the reference
    (resource vector entries) but workers granted TPU get exclusive chip
    visibility via TPU_VISIBLE_CHIPS/JAX_PLATFORMS env, because on TPU a
    chip belongs to exactly one process (no MPS-style sharing).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from ray_tpu.core import rpc
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.resources import CPU, TPU, ResourceSet
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec

# Object states
PENDING = "PENDING"
READY = "READY"
ERRORED = "ERRORED"

# Actor states (mirrors reference gcs_actor_manager.h state machine)
A_PENDING = "PENDING_CREATION"
A_ALIVE = "ALIVE"
A_RESTARTING = "RESTARTING"
A_DEAD = "DEAD"


@dataclass
class ObjectEntry:
    state: str = PENDING
    size: int = 0
    inline: Optional[bytes] = None
    in_shm: bool = False
    refcount: int = 1
    is_error: bool = False
    subscribers: List[rpc.Connection] = field(default_factory=list)
    producing_task: Optional[str] = None  # task hex, lineage hook


@dataclass
class WorkerInfo:
    worker_hex: str
    conn: Optional[rpc.Connection] = None
    pid: int = 0
    address: str = ""  # worker's own rpc server (direct actor transport)
    kind: str = "pool"  # pool | actor | driver
    env_key: str = ""
    state: str = "starting"  # starting | idle | busy | dead
    current_task: Optional[str] = None
    acquired: ResourceSet = field(default_factory=ResourceSet)
    actor_hex: str = ""
    proc: Optional[subprocess.Popen] = None


@dataclass
class ActorEntry:
    spec: ActorCreationSpec
    state: str = A_PENDING
    worker_hex: str = ""
    address: str = ""
    death_reason: str = ""
    subscribers: List[rpc.Connection] = field(default_factory=list)


@dataclass
class TaskRecord:
    spec: TaskSpec
    state: str = "PENDING"  # PENDING | RUNNING | FINISHED | FAILED
    worker_hex: str = ""
    submitted_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0


_SITE_PACKAGES: Optional[str] = None


def _site_packages() -> str:
    """Site-package dirs joined for PYTHONPATH (cached)."""
    global _SITE_PACKAGES
    if _SITE_PACKAGES is None:
        import site

        paths = list(site.getsitepackages())
        usp = site.getusersitepackages()
        if isinstance(usp, str):
            paths.append(usp)
        _SITE_PACKAGES = os.pathsep.join(
            p for p in paths if os.path.isdir(p))
    return _SITE_PACKAGES


class ControlServer:
    def __init__(self, session_id: str, config: Config, resources: ResourceSet,
                 session_dir: str, namespace: str = ""):
        self.session_id = session_id
        self.config = config
        self.session_dir = session_dir
        self.namespace = namespace
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)

        self.lock = threading.RLock()
        self.objects: Dict[str, ObjectEntry] = {}
        self.workers: Dict[str, WorkerInfo] = {}
        self.actors: Dict[str, ActorEntry] = {}
        self.named_actors: Dict[tuple, str] = {}
        self.kv: Dict[str, bytes] = {}
        self.funcs: Dict[str, bytes] = {}
        # In-flight actor-task return objects: actor hex -> pending obj
        # hexes, and the reverse map. Used to fail callers' gets when an
        # actor dies with tasks in its queue (the reference fails these via
        # DirectActorTaskSubmitter::DisconnectActor).
        self.actor_inflight: Dict[str, Set[str]] = {}
        self.obj_actor: Dict[str, str] = {}
        self.tasks: Dict[str, TaskRecord] = {}
        self.pending_tasks: List[TaskSpec] = []
        self.pending_actors: List[ActorCreationSpec] = []

        self.total_resources = resources
        self.available = resources
        self.store = ShmObjectStore(session_id, config.shm_dir)

        self._wake = threading.Event()
        self._stopped = threading.Event()
        self.server = rpc.Server(self._handle, on_disconnect=self._on_disconnect)
        self._sched_thread = threading.Thread(
            target=self._schedule_loop, name="scheduler", daemon=True
        )
        self._sched_thread.start()

    # ------------------------------------------------------------------
    @property
    def address(self) -> str:
        return self.server.address

    def stop(self):
        self._stopped.set()
        self._wake.set()
        with self.lock:
            workers = list(self.workers.values())
        for w in workers:
            if w.conn is not None and w.kind != "driver":
                try:
                    w.conn.push({"op": "exit"})
                except Exception:
                    pass
        procs = [w.proc for w in workers if w.proc is not None]
        deadline = time.monotonic() + 1.0
        while procs and time.monotonic() < deadline:
            procs = [p for p in procs if p.poll() is None]
            if procs:
                time.sleep(0.02)
        for p in procs:  # stragglers: escalate
            try:
                p.kill()
            except OSError:
                pass
        self.server.stop()
        self.store.cleanup()

    # ------------------------------------------------------------------
    # RPC dispatch
    def _handle(self, conn: rpc.Connection, msg: dict):
        op = msg["op"]
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown control op: {op}")
        return fn(conn, msg)

    def _on_disconnect(self, conn: rpc.Connection):
        worker_hex = conn.meta.get("worker_hex")
        if worker_hex is None:
            return
        with self.lock:
            w = self.workers.get(worker_hex)
            if w is None or w.state == "dead":
                return
            self._mark_worker_dead(w, "connection lost")
        self._wake.set()

    def _mark_worker_dead(self, w: WorkerInfo, reason: str):
        """Called with lock held. Fail/retry its task, kill/restart its actor."""
        w.state = "dead"
        w.conn = None
        self.available = self.available.add(w.acquired)
        w.acquired = ResourceSet()
        if w.current_task:
            rec = self.tasks.get(w.current_task)
            if rec is not None and rec.state == "RUNNING":
                spec = rec.spec
                if spec.retry_count < spec.max_retries:
                    spec.retry_count += 1
                    rec.state = "PENDING"
                    rec.worker_hex = ""
                    self.pending_tasks.append(spec)
                else:
                    rec.state = "FAILED"
                    self._fail_task_returns(spec, f"worker died: {reason}")
            w.current_task = None
        if w.actor_hex:
            entry = self.actors.get(w.actor_hex)
            if entry is not None and entry.state not in (A_DEAD,):
                spec = entry.spec
                # Tasks already delivered to the dead process are lost either
                # way; fail their return objects so callers' gets raise
                # instead of hanging.
                self._fail_actor_inflight(w.actor_hex, reason)
                if spec.restart_count < spec.max_restarts:
                    spec.restart_count += 1
                    entry.state = A_RESTARTING
                    entry.worker_hex = ""
                    entry.address = ""
                    self._push_actor_update(entry, w.actor_hex)
                    self.pending_actors.append(spec)
                else:
                    entry.state = A_DEAD
                    entry.death_reason = reason
                    self._push_actor_update(entry, w.actor_hex)

    def _fail_actor_inflight(self, actor_hex: str, reason: str):
        """Lock held. Store ActorDiedError into every unfinished return
        object of tasks already sent to this actor."""
        from ray_tpu.core.exceptions import ActorDiedError
        from ray_tpu.core.serialization import serialize

        pending = self.actor_inflight.pop(actor_hex, None)
        if not pending:
            return
        data = serialize(
            ActorDiedError(actor_hex, f"worker died: {reason}")).to_bytes()
        for obj_hex in list(pending):
            self.obj_actor.pop(obj_hex, None)
            entry = self.objects.get(obj_hex)
            if entry is None or entry.state == PENDING:
                self._store_object_locked(
                    obj_hex, inline=data, size=len(data), is_error=True)

    def _fail_task_returns(self, spec: TaskSpec, reason: str):
        """Lock held. Store WorkerCrashedError in the task's return objects."""
        from ray_tpu.core.exceptions import WorkerCrashedError
        from ray_tpu.core.serialization import serialize

        err = serialize(WorkerCrashedError(f"task {spec.name or spec.task_id.hex()}: {reason}"))
        data = err.to_bytes()
        for oid in spec.return_ids:
            self._store_object_locked(oid.hex(), inline=data, size=len(data),
                                      is_error=True)

    # ------------------------------------------------------------------
    # Registration
    def _op_register(self, conn, msg):
        worker_hex = msg["worker_hex"]
        with self.lock:
            w = self.workers.get(worker_hex)
            if w is None:
                w = WorkerInfo(worker_hex=worker_hex)
                self.workers[worker_hex] = w
            w.conn = conn
            w.pid = msg.get("pid", 0)
            w.address = msg.get("address", "")
            w.kind = msg.get("kind", w.kind or "pool")
            w.env_key = msg.get("env_key", w.env_key)
            conn.meta["worker_hex"] = worker_hex
            # Pool workers stay "starting" until they send worker_online
            # (hooks installed); dispatching earlier races task delivery.
            if w.kind == "driver":
                w.state = "driver"
        self._wake.set()
        return {
            "session_id": self.session_id,
            "shm_dir": self.config.shm_dir,
            "session_dir": self.session_dir,
        }

    # ------------------------------------------------------------------
    # Objects
    def _store_object_locked(self, obj_hex: str, *, inline, size, is_error,
                             in_shm: bool = False):
        entry = self.objects.get(obj_hex)
        if entry is None:
            entry = self.objects[obj_hex] = ObjectEntry()
        entry.state = ERRORED if is_error else READY
        entry.inline = inline
        entry.size = size
        entry.in_shm = in_shm
        entry.is_error = is_error
        actor_hex = self.obj_actor.pop(obj_hex, None)
        if actor_hex is not None:
            self.actor_inflight.get(actor_hex, set()).discard(obj_hex)
        subs, entry.subscribers = entry.subscribers, []
        push = self._object_ready_msg(obj_hex, entry)
        for c in subs:
            try:
                c.push(push)
            except Exception:
                pass

    def _object_ready_msg(self, obj_hex, entry):
        return {
            "op": "object_ready",
            "obj": obj_hex,
            "size": entry.size,
            "inline": entry.inline,
            "in_shm": entry.in_shm,
            "is_error": entry.is_error,
        }

    def _op_put_object(self, conn, msg):
        with self.lock:
            self._store_object_locked(
                msg["obj"],
                inline=msg.get("inline"),
                size=msg["size"],
                is_error=msg.get("is_error", False),
                in_shm=msg.get("in_shm", False),
            )
        self._wake.set()

    def _op_subscribe_object(self, conn, msg):
        obj_hex = msg["obj"]
        with self.lock:
            entry = self.objects.get(obj_hex)
            if entry is None:
                entry = self.objects[obj_hex] = ObjectEntry(refcount=0)
            if entry.state in (READY, ERRORED):
                conn.push(self._object_ready_msg(obj_hex, entry))
            else:
                entry.subscribers.append(conn)

    def _op_incref(self, conn, msg):
        with self.lock:
            entry = self.objects.get(msg["obj"])
            if entry is not None:
                entry.refcount += msg.get("n", 1)

    def _op_decref(self, conn, msg):
        to_delete = []
        with self.lock:
            obj_hex = msg["obj"]
            entry = self.objects.get(obj_hex)
            if entry is None:
                return
            entry.refcount -= msg.get("n", 1)
            if entry.refcount <= 0 and entry.state in (READY, ERRORED):
                del self.objects[obj_hex]
                if entry.in_shm:
                    to_delete.append(obj_hex)
        for obj_hex in to_delete:
            self.store.delete(ObjectID.from_hex(obj_hex))

    def _op_register_objects(self, conn, msg):
        """Pre-register return objects of direct (actor) tasks with one ref
        held by the submitter, mirroring TaskManager::AddPendingTask return
        registration (reference core_worker.cc:2231).  When tied to an
        actor, track them so actor death fails outstanding callers."""
        actor_hex = msg.get("actor")
        with self.lock:
            for obj_hex in msg["objs"]:
                self.objects.setdefault(obj_hex, ObjectEntry())
                if actor_hex:
                    self.actor_inflight.setdefault(
                        actor_hex, set()).add(obj_hex)
                    self.obj_actor[obj_hex] = actor_hex

    def _op_free_objects(self, conn, msg):
        with self.lock:
            for obj_hex in msg["objs"]:
                entry = self.objects.pop(obj_hex, None)
                if entry is not None and entry.in_shm:
                    self.store.delete(ObjectID.from_hex(obj_hex))

    # ------------------------------------------------------------------
    # Functions (counterpart of _private/function_manager.py export tables)
    def _op_put_func(self, conn, msg):
        with self.lock:
            self.funcs.setdefault(msg["func_id"], msg["blob"])

    def _op_get_func(self, conn, msg):
        with self.lock:
            return self.funcs.get(msg["func_id"])

    # ------------------------------------------------------------------
    # KV store (reference: gcs_kv_manager / experimental/internal_kv.py)
    def _op_kv_put(self, conn, msg):
        with self.lock:
            key = msg["key"]
            if msg.get("overwrite", True) or key not in self.kv:
                self.kv[key] = msg["value"]
                return True
            return False

    def _op_kv_get(self, conn, msg):
        with self.lock:
            return self.kv.get(msg["key"])

    def _op_kv_del(self, conn, msg):
        with self.lock:
            return self.kv.pop(msg["key"], None) is not None

    def _op_kv_keys(self, conn, msg):
        prefix = msg.get("prefix", "")
        with self.lock:
            return [k for k in self.kv if k.startswith(prefix)]

    def _op_kv_exists(self, conn, msg):
        with self.lock:
            return msg["key"] in self.kv

    # ------------------------------------------------------------------
    # Tasks
    def _op_submit_task(self, conn, msg):
        spec: TaskSpec = msg["spec"]
        with self.lock:
            for oid in spec.return_ids:
                self.objects.setdefault(oid.hex(), ObjectEntry(
                    producing_task=spec.task_id.hex()))
            self.tasks[spec.task_id.hex()] = TaskRecord(
                spec=spec, submitted_at=time.time())
            self.pending_tasks.append(spec)
        self._wake.set()

    def _op_task_done(self, conn, msg):
        with self.lock:
            rec = self.tasks.get(msg["task_id"])
            worker_hex = conn.meta.get("worker_hex")
            w = self.workers.get(worker_hex) if worker_hex else None
            if rec is not None:
                rec.state = "FAILED" if msg.get("failed") else "FINISHED"
                rec.finished_at = time.time()
            if w is not None and w.kind == "pool":
                w.state = "idle"
                w.current_task = None
                self.available = self.available.add(w.acquired)
                w.acquired = ResourceSet()
        self._wake.set()

    # ------------------------------------------------------------------
    # Actors
    def _op_create_actor(self, conn, msg):
        spec: ActorCreationSpec = msg["spec"]
        with self.lock:
            entry = ActorEntry(spec=spec)
            self.actors[spec.actor_id.hex()] = entry
            if spec.name:
                key = (spec.namespace, spec.name)
                if key in self.named_actors:
                    entry.state = A_DEAD
                    entry.death_reason = f"name {spec.name!r} already taken"
                    self._push_actor_update(entry, spec.actor_id.hex())
                    return
                self.named_actors[key] = spec.actor_id.hex()
            self.pending_actors.append(spec)
        self._wake.set()

    def _op_actor_ready(self, conn, msg):
        actor_hex = msg["actor"]
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                return
            if entry.state == A_DEAD:
                # Killed while the worker was still creating the instance —
                # don't resurrect; tell the worker to exit (zombie would
                # otherwise hold its resource allocation).
                try:
                    conn.push({"op": "exit"})
                except Exception:
                    pass
                return
            entry.state = A_ALIVE
            entry.address = msg["address"]
            self._push_actor_update(entry, actor_hex)

    def _op_actor_creation_failed(self, conn, msg):
        actor_hex = msg["actor"]
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                return
            entry.state = A_DEAD
            entry.death_reason = msg.get("reason", "creation failed")
            self._push_actor_update(entry, actor_hex)

    def _op_subscribe_actor(self, conn, msg):
        actor_hex = msg["actor"]
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                conn.push({"op": "actor_update", "actor": actor_hex,
                           "state": A_DEAD, "address": "",
                           "reason": "no such actor"})
                return
            conn.push(self._actor_update_msg(entry, actor_hex))
            if entry.state not in (A_DEAD,):
                entry.subscribers.append(conn)

    def _op_kill_actor(self, conn, msg):
        actor_hex = msg["actor"]
        no_restart = msg.get("no_restart", True)
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                return
            if no_restart:
                entry.spec.max_restarts = entry.spec.restart_count
            w = self.workers.get(entry.worker_hex)
            if w is not None and w.conn is not None:
                try:
                    w.conn.push({"op": "exit"})
                except Exception:
                    pass
            if entry.state == A_PENDING or (w is None and entry.state != A_DEAD):
                entry.state = A_DEAD
                entry.death_reason = "killed"
                self.pending_actors = [
                    s for s in self.pending_actors
                    if s.actor_id.hex() != actor_hex
                ]
                self._fail_actor_inflight(actor_hex, "killed")
                self._push_actor_update(entry, actor_hex)

    def _actor_update_msg(self, entry: ActorEntry, actor_hex: str):
        return {
            "op": "actor_update",
            "actor": actor_hex,
            "state": entry.state,
            "address": entry.address,
            "reason": entry.death_reason,
        }

    def _push_actor_update(self, entry: ActorEntry, actor_hex: str):
        msg = self._actor_update_msg(entry, actor_hex)
        subs = list(entry.subscribers)
        if entry.state == A_DEAD:
            entry.subscribers = []
        for c in subs:
            try:
                c.push(msg)
            except Exception:
                pass

    def _op_get_named_actor(self, conn, msg):
        key = (msg.get("namespace", ""), msg["name"])
        with self.lock:
            actor_hex = self.named_actors.get(key)
            if actor_hex is None:
                return None
            entry = self.actors.get(actor_hex)
            if entry is None or entry.state == A_DEAD:
                return None
            return {"actor": actor_hex, "class_id": entry.spec.class_id,
                    "state": entry.state, "address": entry.address}

    def _op_list_named_actors(self, conn, msg):
        with self.lock:
            out = []
            for (ns, name), actor_hex in self.named_actors.items():
                entry = self.actors.get(actor_hex)
                if entry is not None and entry.state != A_DEAD:
                    out.append({"name": name, "namespace": ns})
            return out

    # ------------------------------------------------------------------
    # State API (reference: util/state — ray list tasks/actors/...)
    def _op_cluster_resources(self, conn, msg):
        return self.total_resources.to_dict()

    def _op_available_resources(self, conn, msg):
        with self.lock:
            return self.available.to_dict()

    def _op_list_tasks(self, conn, msg):
        with self.lock:
            return [
                {"task_id": h, "name": r.spec.name, "state": r.state,
                 "worker": r.worker_hex,
                 "duration_s": (r.finished_at - r.started_at)
                 if r.finished_at else None}
                for h, r in self.tasks.items()
            ]

    def _op_list_actors(self, conn, msg):
        with self.lock:
            return [
                {"actor_id": h, "state": e.state, "name": e.spec.name,
                 "class": e.spec.class_id.split(":")[0],
                 "pid": (self.workers.get(e.worker_hex).pid
                         if e.worker_hex in self.workers else None)}
                for h, e in self.actors.items()
            ]

    def _op_list_objects(self, conn, msg):
        with self.lock:
            return [
                {"object_id": h, "state": e.state, "size": e.size,
                 "refcount": e.refcount, "in_shm": e.in_shm}
                for h, e in self.objects.items()
            ]

    def _op_list_workers(self, conn, msg):
        with self.lock:
            return [
                {"worker_id": h, "kind": w.kind, "state": w.state,
                 "pid": w.pid, "actor": w.actor_hex}
                for h, w in self.workers.items()
            ]

    def _op_ping(self, conn, msg):
        return "pong"

    # ------------------------------------------------------------------
    # Scheduler (counterpart of ClusterTaskManager::ScheduleAndDispatchTasks)
    def _schedule_loop(self):
        while not self._stopped.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stopped.is_set():
                return
            try:
                self._schedule_once()
            except Exception:
                import traceback

                traceback.print_exc()

    def _deps_ready(self, spec: TaskSpec) -> bool:
        for arg in spec.args:
            if arg.is_ref:
                entry = self.objects.get(arg.object_hex)
                if entry is None or entry.state == PENDING:
                    return False
        return True

    def _schedule_once(self):
        with self.lock:
            # 1. actors first (they need fresh workers)
            still_pending_actors = []
            to_spawn = []
            for spec in self.pending_actors:
                need = ResourceSet(spec.resources)
                if need.is_subset_of(self.available):
                    self.available = self.available.subtract(need)
                    to_spawn.append((spec, need))
                else:
                    still_pending_actors.append(spec)
            self.pending_actors = still_pending_actors

            # 2. normal tasks to idle pool workers
            dispatches = []
            still_pending = []
            idle = {
                h: w for h, w in self.workers.items()
                if w.kind == "pool" and w.state == "idle" and w.conn is not None
            }
            n_workers = sum(1 for w in self.workers.values()
                            if w.kind == "pool" and w.state != "dead")
            # Workers already starting, per env_key: spawn only the deficit
            # (resource-feasible demand minus workers already on the way),
            # mirroring WorkerPool prestart accounting (worker_pool.h:159).
            starting: Dict[str, int] = {}
            for w in self.workers.values():
                if w.kind == "pool" and w.state == "starting":
                    starting[w.env_key] = starting.get(w.env_key, 0) + 1
            spawned_pool = 0
            # Virtual availability: resources that *would* be in use if every
            # dispatchable-but-workerless task had its worker already.
            avail_virtual = self.available
            for spec in self.pending_tasks:
                if not self._deps_ready(spec):
                    still_pending.append(spec)
                    continue
                need = ResourceSet(spec.resources)
                if not need.is_subset_of(self.available):
                    still_pending.append(spec)
                    continue
                env_key = self._env_key_for(spec.resources, spec.runtime_env)
                worker = next(
                    (w for w in idle.values() if w.env_key == env_key), None)
                if worker is None:
                    if need.is_subset_of(avail_virtual):
                        avail_virtual = avail_virtual.subtract(need)
                        if starting.get(env_key, 0) > 0:
                            starting[env_key] -= 1  # one already on the way
                        elif (n_workers + spawned_pool
                                < self.config.max_workers_per_node):
                            self._spawn_worker(env_key=env_key, kind="pool")
                            spawned_pool += 1
                    still_pending.append(spec)
                    continue
                del idle[worker.worker_hex]
                self.available = self.available.subtract(need)
                if need.is_subset_of(avail_virtual):
                    avail_virtual = avail_virtual.subtract(need)
                worker.acquired = need
                worker.state = "busy"
                worker.current_task = spec.task_id.hex()
                rec = self.tasks.get(spec.task_id.hex())
                if rec is not None:
                    rec.state = "RUNNING"
                    rec.worker_hex = worker.worker_hex
                    rec.started_at = time.time()
                dispatches.append((worker, spec))
            self.pending_tasks = still_pending

            for spec, need in to_spawn:
                w = self._spawn_worker(
                    env_key=self._env_key_for(spec.resources, spec.runtime_env),
                    kind="actor")
                w.acquired = need
                w.actor_hex = spec.actor_id.hex()
                entry = self.actors.get(spec.actor_id.hex())
                if entry is not None:
                    entry.worker_hex = w.worker_hex
                # queue the creation spec; delivered when the worker registers
                w.pending_create = spec  # type: ignore[attr-defined]

        for worker, spec in dispatches:
            try:
                worker.conn.push({"op": "execute_task", "spec": spec})
            except Exception:
                with self.lock:
                    self._mark_worker_dead(worker, "push failed")

    def _env_key_for(self, resources: Dict[str, float],
                     runtime_env: Optional[dict]) -> str:
        tpu = resources.get(TPU, 0) if resources else 0
        env_part = ""
        if runtime_env:
            import hashlib
            import json

            env_part = hashlib.sha1(
                json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:8]
        return f"tpu{int(tpu)}-{env_part}"

    # ------------------------------------------------------------------
    # Worker pool (counterpart of raylet WorkerPool::StartWorkerProcess)
    def _spawn_worker(self, env_key: str, kind: str) -> WorkerInfo:
        """Lock held."""
        worker_id = WorkerID.from_random()
        w = WorkerInfo(worker_hex=worker_id.hex(), kind=kind, env_key=env_key,
                       state="starting")
        self.workers[worker_id.hex()] = w

        env = dict(os.environ)
        env["RAY_TPU_CONTROL_ADDR"] = self.address
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_SESSION_ID"] = self.session_id
        env["RAY_TPU_WORKER_KIND"] = kind
        env["RAY_TPU_ENV_KEY"] = env_key
        env["RAY_TPU_NAMESPACE"] = self.namespace
        cmd = [sys.executable, "-m", "ray_tpu.core.worker"]
        if env_key.startswith("tpu0") or not env_key.startswith("tpu"):
            # CPU-only worker: never let it grab the TPU runtime, and skip
            # site initialization — the environment's sitecustomize imports
            # jax (~1.7 s) into every interpreter, which a CPU pool worker
            # doesn't need.  Site-packages go back on the path via PYTHONPATH.
            env["JAX_PLATFORMS"] = "cpu"
            extra = [p for p in (_site_packages(), env.get("PYTHONPATH"))
                     if p]
            if extra:
                env["PYTHONPATH"] = os.pathsep.join(extra)
            cmd = [sys.executable, "-S", "-m", "ray_tpu.core.worker"]
        log_base = os.path.join(self.session_dir, "logs",
                                f"worker-{worker_id.hex()[:8]}")
        stdout = open(log_base + ".out", "ab")
        stderr = open(log_base + ".err", "ab")
        proc = subprocess.Popen(
            cmd, env=env, stdout=stdout, stderr=stderr,
            cwd=os.getcwd(),
        )
        w.proc = proc
        w.pid = proc.pid
        return w

    def deliver_pending_create(self, w: WorkerInfo):
        spec = getattr(w, "pending_create", None)
        if spec is not None and w.conn is not None:
            w.pending_create = None  # type: ignore[attr-defined]
            w.conn.push({"op": "create_actor_instance", "spec": spec})

    def _op_worker_online(self, conn, msg):
        """Worker is fully initialized: mark schedulable, deliver queued
        actor creation."""
        worker_hex = conn.meta.get("worker_hex")
        with self.lock:
            w = self.workers.get(worker_hex)
            if w is None:
                return
            if w.kind == "pool" and w.state == "starting":
                w.state = "idle"
            self.deliver_pending_create(w)
        self._wake.set()
