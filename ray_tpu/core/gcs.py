"""Control server: object directory, actor registry, KV, scheduler, worker pool.

This is the single-node fusion of the reference's GCS server
(src/ray/gcs/gcs_server/gcs_server.cc — actor/node/KV/pubsub managers) and
raylet (src/ray/raylet/node_manager.cc — ClusterTaskManager / LocalTaskManager
/ WorkerPool).  It runs as threads inside the head process and speaks the
rpc.py framed protocol to driver and worker processes.

Design deviations from the reference, deliberate for the TPU-first rebuild:
  - Small objects live in the directory itself rather than in per-owner
    memory stores; on a single node the directory IS the owner's metadata
    table.  Multi-node ownership (owner-resident values + location lookups,
    reference reference_count.h / ownership_based_object_directory.cc) is
    layered on in the multi-host control plane.
  - Scheduling is event-driven FIFO + resource fit over one node; the
    hybrid pack/spread policy slot is where multi-node placement goes.
  - TPU chips are scheduled like GPUs in the reference
    (resource vector entries) but workers granted TPU get exclusive chip
    visibility via TPU_VISIBLE_CHIPS/JAX_PLATFORMS env, because on TPU a
    chip belongs to exactly one process (no MPS-style sharing).
"""

from __future__ import annotations

import itertools
import logging
import os
import subprocess
import sys
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from ray_tpu.core import object_plane, rpc
from ray_tpu.core.config import Config
from ray_tpu.core.exceptions import ObjectLostError
from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.resources import CPU, TPU, ResourceSet
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.core.log_once import warn_once

logger = logging.getLogger(__name__)

# Object states
PENDING = "PENDING"
READY = "READY"
ERRORED = "ERRORED"

# Actor states (mirrors reference gcs_actor_manager.h state machine)
A_PENDING = "PENDING_CREATION"
A_ALIVE = "ALIVE"
A_RESTARTING = "RESTARTING"
A_DEAD = "DEAD"


@dataclass
class ObjectEntry:
    state: str = PENDING
    size: int = 0
    inline: Optional[bytes] = None
    in_shm: bool = False
    refcount: int = 1
    is_error: bool = False
    subscribers: List[rpc.Connection] = field(default_factory=list)
    producing_task: Optional[str] = None  # task hex, lineage hook
    spilled_uri: Optional[str] = None  # external-storage URI when spilled
    restoring: bool = False
    stored_at: float = 0.0
    # Times this object's value was re-created by lineage reconstruction.
    reconstructions: int = 0
    # Which node's shm arena holds the primary copy ("head" = the head's
    # arena, shared by logical/fake-cluster nodes).  Counterpart of the
    # reference's object directory locations
    # (ownership_based_object_directory.cc).
    node_id: str = "head"
    # Nodes that cached a pulled replica (so freeing the object can
    # delete every arena copy, not just the primary's).
    replicas: Set[str] = field(default_factory=set)
    # Nodes with an in-flight PullManager pull (object_pull_started
    # announce): node_id -> announce time.  The locality tie-break
    # credits these too — a task chasing an object already in transit
    # to a node should land there, not trigger a second transfer.
    # Entries expire (stale announce) and clear on replica landing.
    pulling: Dict[str, float] = field(default_factory=dict)


@dataclass
class NodeState:
    """One logical node: a resource pool + its worker processes.

    Counterpart of a raylet's local resource view (raylet/node_manager.h).
    In-process ("fake cluster") nodes partition the head's control plane the
    way the reference's cluster_utils.Cluster partitions one host into many
    raylets (python/ray/cluster_utils.py:135); worker processes are real
    either way.
    """

    node_id: str
    total: ResourceSet
    available: ResourceSet
    alive: bool = True
    is_head: bool = False
    # Graceful drain (reference DrainRaylet, node_manager.proto:401 /
    # autoscaler DrainNode, autoscaler.proto:334): a draining node is
    # still alive but no longer schedulable; running work finishes,
    # sole-copy objects migrate to a survivor, idle PG bundles
    # reschedule, then the node terminates WITHOUT lineage re-execution.
    draining: bool = False
    drain_reason: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    # Real (remote-host) nodes: set by register_node.  Logical nodes
    # (fake-cluster partitions) leave these empty and share the head's
    # arena/worker spawner.
    address: str = ""  # the node manager's object-plane rpc server
    conn: Optional[rpc.Connection] = None  # its control connection
    store_key: str = ""  # its arena name ('' = shares the head arena)
    shm_dir: str = ""
    # Last host-stats report from the node's reporter
    # (dashboard/reporter.py; reference reporter_agent.py).
    stats: Dict[str, Any] = field(default_factory=dict)
    # When that report arrived (time.time()); the health watchdog
    # flags remote nodes whose reporter has gone silent.
    stats_at: float = 0.0

    @property
    def is_remote(self) -> bool:
        return self.conn is not None or bool(self.store_key)

    @property
    def schedulable(self) -> bool:
        """Scheduling eligibility: alive AND not draining (a draining
        node stops accepting leases/placements immediately)."""
        return self.alive and not self.draining


@dataclass
class Bundle:
    """A placement-group bundle: resources reserved on one node."""

    index: int
    node_id: str
    reserved: ResourceSet
    available: ResourceSet


@dataclass
class PlacementGroupEntry:
    """Counterpart of GcsPlacementGroupManager state
    (gcs/gcs_server/gcs_placement_group_manager.h:230)."""

    pg_hex: str
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    bundle_specs: List[Dict[str, float]]
    state: str = "PENDING"  # PENDING | CREATED | REMOVED | INFEASIBLE
    bundles: List[Bundle] = field(default_factory=list)
    ready_obj: str = ""  # object set when CREATED (PlacementGroup.ready())
    name: str = ""


@dataclass
class WorkerInfo:
    worker_hex: str
    conn: Optional[rpc.Connection] = None
    pid: int = 0
    address: str = ""  # worker's own rpc server (direct actor transport)
    kind: str = "pool"  # pool | actor | driver
    env_key: str = ""
    state: str = "starting"  # starting | idle | busy | dead
    current_task: Optional[str] = None
    acquired: ResourceSet = field(default_factory=ResourceSet)
    actor_hex: str = ""
    proc: Optional[subprocess.Popen] = None
    node_id: str = ""
    # where acquired resources were charged: ("node", node_id) or
    # ("pg", pg_hex, bundle_index)
    charge: tuple = ()
    # state == "leased": the owner (worker hex) this worker is leased to
    # (reference: a granted lease binds the worker to the requesting
    # CoreWorkerDirectTaskSubmitter, direct_task_transport.h:353)
    leased_to: str = ""
    # When the spawn was requested; remote spawns (proc is None) that
    # never register are reaped after worker_register_timeout_s.
    spawned_at: float = 0.0


@dataclass
class ActorEntry:
    spec: ActorCreationSpec
    state: str = A_PENDING
    worker_hex: str = ""
    address: str = ""
    death_reason: str = ""
    subscribers: List[rpc.Connection] = field(default_factory=list)


@dataclass
class TaskRecord:
    spec: TaskSpec
    state: str = "PENDING"  # PENDING | RUNNING | FINISHED | FAILED
    worker_hex: str = ""
    submitted_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    # Streamed-event extras (worker _buffer_task_event deltas): arrival
    # time on the executing worker, retry ordinal, and the trace span
    # this execution belongs to (util/tracing.py propagation).
    received_at: float = 0.0
    retry_count: int = 0
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    # Total READY shm bytes of the spec's ref args, captured while the
    # task (and therefore its args) is alive; -1 = not yet computed.
    # The watchdog buckets straggler baselines by this so a 1 GiB-input
    # sibling is never judged against 1 KiB-input completions.
    arg_bytes: int = -1


def _sum_bundles(bundle_specs: List[Dict[str, float]]) -> Dict[str, float]:
    total: Dict[str, float] = {}
    for b in bundle_specs:
        for k, v in b.items():
            total[k] = total.get(k, 0.0) + v
    return total


_TRUE_BYTES: Optional[bytes] = None


def _serialized_true() -> bytes:
    global _TRUE_BYTES
    if _TRUE_BYTES is None:
        from ray_tpu.core.serialization import serialize

        _TRUE_BYTES = serialize(True).to_bytes()
    return _TRUE_BYTES


_SITE_PACKAGES: Optional[str] = None


def _site_packages() -> str:
    """Site-package dirs joined for PYTHONPATH (cached)."""
    global _SITE_PACKAGES
    if _SITE_PACKAGES is None:
        import site

        paths = list(site.getsitepackages())
        usp = site.getusersitepackages()
        if isinstance(usp, str):
            paths.append(usp)
        _SITE_PACKAGES = os.pathsep.join(
            p for p in paths if os.path.isdir(p))
    return _SITE_PACKAGES


_FALSY = ("0", "false", "no", "off")


def _env_int(name: str, default: int, floor: int) -> int:
    try:
        v = int(os.environ.get(name, str(default)))
    except ValueError:
        v = default
    return max(floor, v)


def _env_float(name: str, default: float, floor: float) -> float:
    try:
        v = float(os.environ.get(name, str(default)))
    except ValueError:
        v = default
    return max(floor, v)


def _watchdog_enabled() -> bool:
    """RAY_TPU_WATCHDOG gate, read once at head construction: when off
    the watchdog object is never built and the scheduler loop's only
    trace of it is one `is not None` check."""
    return os.environ.get(
        "RAY_TPU_WATCHDOG", "1").strip().lower() not in _FALSY


class _Watchdog:
    """Straggler / node-health detector (head-side).

    Counterpart of the operational watchdogs TPU-pod training stacks
    grow by necessity: at scale the dominant failures are not crashes
    but tasks that silently run 10x longer than their siblings and
    hosts whose reporters go quiet.  The detector compares each RUNNING
    task's age against the completed-duration distribution of its
    same-name siblings (percentile x multiplier threshold), and each
    remote node's last stats report against a heartbeat timeout.
    Verdicts land on the flight recorder's "health" lane and the
    ray_tpu_stragglers_total / ray_tpu_node_unhealthy_total counters —
    detection only, no automatic kills (the OOM killer owns policy).

    Knobs: RAY_TPU_WATCHDOG (off switch), _INTERVAL_S (tick period,
    default 5), _MIN_SAMPLES (sibling completions required, default 5),
    _PERCENTILE (default 95), _MULTIPLIER (threshold factor, default
    3), _MIN_AGE_S (never flag younger than this, default 1),
    _HEARTBEAT_TIMEOUT_S (stale-reporter cutoff, default 30)."""

    def __init__(self, server: "ControlServer"):
        self.server = server
        self.interval_s = _env_float(
            "RAY_TPU_WATCHDOG_INTERVAL_S", 5.0, 0.05)
        self.min_samples = _env_int(
            "RAY_TPU_WATCHDOG_MIN_SAMPLES", 5, 1)
        self.percentile = min(100.0, _env_float(
            "RAY_TPU_WATCHDOG_PERCENTILE", 95.0, 1.0))
        self.multiplier = _env_float(
            "RAY_TPU_WATCHDOG_MULTIPLIER", 3.0, 1.0)
        self.min_age_s = _env_float(
            "RAY_TPU_WATCHDOG_MIN_AGE_S", 1.0, 0.0)
        self.heartbeat_timeout_s = _env_float(
            "RAY_TPU_WATCHDOG_HEARTBEAT_TIMEOUT_S", 30.0, 1.0)
        self._last_tick = 0.0
        self._flagged_tasks: Set[str] = set()  # flag once per task
        self._unhealthy_nodes: Set[str] = set()
        # Device-plane rules (PR 19): recompile storms flag once per
        # (worker, function); HBM watermark alerts re-arm when the
        # occupancy drops back under the threshold.
        self.recompile_max = _env_int(
            "RAY_TPU_DEVICE_RECOMPILE_MAX", 8, 1)
        self.hbm_watermark = _env_float(
            "RAY_TPU_DEVICE_HBM_WATERMARK", 0.9, 0.01)
        self._flagged_recompiles: Set[tuple] = set()
        self._hbm_alerted: Set[str] = set()
        # Totals for /api/profile and tests (counters may be None when
        # metrics failed to import).
        self.stragglers_flagged = 0
        self.nodes_flagged = 0
        self.recompile_storms_flagged = 0
        self.hbm_alerts = 0

    @staticmethod
    def _percentile_of(sorted_vals: List[float], pct: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = int(len(sorted_vals) * pct / 100.0)
        return sorted_vals[min(idx, len(sorted_vals) - 1)]

    @staticmethod
    def _size_bucket(arg_bytes: int) -> int:
        """Arg-size class: 0 for no/unknown args, then one bucket per
        16x of total READY-arg bytes (1 KiB and 4 KiB share a bucket;
        1 KiB and 1 GiB never do).  Coarse on purpose — buckets must
        collect min_samples completions before they gate anything."""
        if arg_bytes <= 0:
            return 0
        return max(1, int(arg_bytes).bit_length() // 4)

    def maybe_tick(self) -> None:
        now = time.time()
        if now - self._last_tick < self.interval_s:
            return
        self._last_tick = now
        try:
            self.tick(now)
        except Exception:
            pass  # detection must never take down the scheduler

    def tick(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self._check_stragglers(now)
        self._check_nodes(now)
        self._check_device(now)

    def _check_stragglers(self, now: float) -> None:
        srv = self.server
        # Completed-sibling durations, both pooled per task name and
        # split per (name, arg-size bucket): heterogeneous batches
        # (same function over 1 KiB vs 1 GiB inputs) threshold within
        # their own size class when it has enough samples, falling back
        # to the pooled distribution when it does not.
        durations: Dict[str, List[float]] = {}
        bucketed: Dict[tuple, List[float]] = {}
        running: List[tuple] = []
        with srv.lock:
            for th, rec in srv.tasks.items():
                name = rec.spec.name or \
                    getattr(rec.spec, "func_id", "")[:8]
                if rec.state == "FINISHED":
                    start = rec.started_at or rec.received_at
                    if start and rec.finished_at > start:
                        dur = rec.finished_at - start
                        durations.setdefault(name, []).append(dur)
                        bucket = self._size_bucket(rec.arg_bytes)
                        bucketed.setdefault((name, bucket),
                                            []).append(dur)
                elif rec.state == "RUNNING" and \
                        th not in self._flagged_tasks:
                    start = rec.started_at or rec.received_at or \
                        rec.submitted_at
                    if start:
                        if rec.arg_bytes < 0:
                            rec.arg_bytes = srv._task_arg_bytes(rec.spec)
                        running.append(
                            (th, name, now - start, rec.worker_hex,
                             rec.arg_bytes))
        for sibs in durations.values():
            sibs.sort()
        for sibs in bucketed.values():
            sibs.sort()
        from ray_tpu.util import flight_recorder

        for th, name, age, worker_hex, arg_bytes in running:
            bucket = self._size_bucket(arg_bytes)
            sibs = bucketed.get((name, bucket))
            pooled = False
            if sibs is None or len(sibs) < self.min_samples:
                sibs = durations.get(name)
                pooled = True
            if sibs is None or len(sibs) < self.min_samples:
                continue
            threshold = max(
                self.min_age_s,
                self._percentile_of(sibs, self.percentile)
                * self.multiplier)
            if age <= threshold:
                continue
            self._flagged_tasks.add(th)
            self.stragglers_flagged += 1
            if srv._m_stragglers is not None:
                srv._m_stragglers.inc()
            flight_recorder.record(
                "health", "straggler", task=th, name=name,
                age_s=round(age, 3), threshold_s=round(threshold, 3),
                siblings=len(sibs), worker=worker_hex,
                arg_bytes=max(0, arg_bytes), size_bucket=bucket,
                pooled_baseline=pooled)

    def _check_nodes(self, now: float) -> None:
        srv = self.server
        stale: List[tuple] = []
        recovered: List[str] = []
        with srv.lock:
            for nid, node in srv.nodes.items():
                # Only remote nodes report via the wire; the head and
                # logical (fake-cluster) nodes share this process.
                if node.is_head or node.conn is None or not node.alive:
                    continue
                seen = node.stats_at
                if seen and now - seen > self.heartbeat_timeout_s:
                    if nid not in self._unhealthy_nodes:
                        stale.append((nid, now - seen))
                elif nid in self._unhealthy_nodes:
                    recovered.append(nid)
        from ray_tpu.util import flight_recorder

        for nid, silent_s in stale:
            self._unhealthy_nodes.add(nid)
            self.nodes_flagged += 1
            if srv._m_node_unhealthy is not None:
                srv._m_node_unhealthy.inc()
            flight_recorder.record(
                "health", "node_unhealthy", node=nid,
                silent_s=round(silent_s, 1),
                timeout_s=self.heartbeat_timeout_s)
        for nid in recovered:
            self._unhealthy_nodes.discard(nid)
            flight_recorder.record("health", "node_recovered", node=nid)

    def _check_device(self, now: float) -> None:
        """Device-plane rules over the latest profile samples (the
        recompile counts and HBM ledger piggybacked by the worker
        sampler): a recompile storm — post-warmup compiles of one
        function past RAY_TPU_DEVICE_RECOMPILE_MAX — flags once per
        (worker, function); an HBM watermark at/over
        RAY_TPU_DEVICE_HBM_WATERMARK alerts and re-arms when the
        reported watermark drops back under."""
        srv = self.server
        with srv.lock:
            latest = {wh: dict(s) for wh, s in srv._profiles.items()}
        storms: List[tuple] = []
        hbm_hits: List[tuple] = []
        hbm_clear: List[str] = []
        for wh, sample in latest.items():
            rec = sample.get("recompiles")
            if isinstance(rec, dict):
                for fn, n in rec.items():
                    try:
                        n = int(n)
                    except (TypeError, ValueError):  # raylint: allow-swallow(a malformed count in one report must not kill the sweep)
                        continue
                    if n > self.recompile_max and \
                            (wh, fn) not in self._flagged_recompiles:
                        self._flagged_recompiles.add((wh, fn))
                        storms.append((wh, fn, n))
            dev = sample.get("device")
            frac = (dev or {}).get("watermark_fraction") \
                if isinstance(dev, dict) else None
            if frac is None:
                frac = sample.get("hbm_watermark_fraction")
            if isinstance(frac, (int, float)) and \
                    not isinstance(frac, bool):
                if frac >= self.hbm_watermark:
                    if wh not in self._hbm_alerted:
                        self._hbm_alerted.add(wh)
                        hbm_hits.append((wh, float(frac)))
                elif wh in self._hbm_alerted:
                    hbm_clear.append(wh)
        from ray_tpu.util import flight_recorder

        for wh, fn, n in storms:
            self.recompile_storms_flagged += 1
            flight_recorder.record(
                "health", "recompile_storm", worker=wh, function=fn,
                recompiles_after_warmup=n,
                threshold=self.recompile_max)
        for wh, frac in hbm_hits:
            self.hbm_alerts += 1
            flight_recorder.record(
                "health", "hbm_watermark", worker=wh,
                watermark_fraction=round(frac, 4),
                threshold=self.hbm_watermark)
        for wh in hbm_clear:
            self._hbm_alerted.discard(wh)
            flight_recorder.record(
                "health", "hbm_watermark_cleared", worker=wh)

    def profile_distributions(self) -> Dict[str, Dict[str, Any]]:
        """Per-worker percentile summaries over the head's profile
        history rings — worker load as a distribution (p50/p95 across
        the ring) instead of whichever sample arrived last."""
        srv = self.server
        with srv.lock:
            rings = {wh: list(ring)
                     for wh, ring in srv._profile_hist.items()
                     if wh in srv.workers
                     and srv.workers[wh].state != "dead"}
        return {wh: _profile_history_summary(samples)
                for wh, samples in rings.items()}

    def snapshot(self) -> Dict[str, Any]:
        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "stragglers_flagged": self.stragglers_flagged,
            "nodes_flagged": self.nodes_flagged,
            "unhealthy_nodes": sorted(self._unhealthy_nodes),
            "recompile_storms_flagged": self.recompile_storms_flagged,
            "recompile_max": self.recompile_max,
            "hbm_alerts": self.hbm_alerts,
            "hbm_watermark": self.hbm_watermark,
            "profile_distributions": self.profile_distributions(),
        }


def _profile_history_summary(samples: List[dict]) -> Dict[str, Any]:
    """p50/p95 per numeric field over one worker's history ring (the
    /api/profile and watchdog distribution view; computed at query
    time, never on the report path)."""
    numeric: Dict[str, List[float]] = {}
    for s in samples:
        for k, v in s.items():
            if k in ("ts", "pid") or isinstance(v, bool):
                continue
            if isinstance(v, (int, float)):
                numeric.setdefault(k, []).append(float(v))
    pcts: Dict[str, Dict[str, float]] = {}
    for k, vals in numeric.items():
        vals.sort()
        pcts[k] = {"p50": _Watchdog._percentile_of(vals, 50.0),
                   "p95": _Watchdog._percentile_of(vals, 95.0)}
    return {
        "samples": len(samples),
        "first_ts": samples[0].get("ts", 0.0) if samples else 0.0,
        "last_ts": samples[-1].get("ts", 0.0) if samples else 0.0,
        "percentiles": pcts,
    }


# ---------------------------------------------------------------------------
# Head scale-out structures (reference: the sharded GCS table layer,
# gcs_table_storage.h — per-key-space partitions so hot paths stop
# serializing on one store — and the raylet's bucketed
# ClusterResourceManager view).


def _gcs_shards() -> int:
    """RAY_TPU_GCS_SHARDS: owner-keyed submit-ingress shards (0 =
    legacy single-lock ingress, used by the paired benchmarks)."""
    try:
        return max(0, int(os.environ.get("RAY_TPU_GCS_SHARDS", "8")))
    except ValueError:
        return 8


def _node_index_enabled() -> bool:
    return os.environ.get("RAY_TPU_NODE_INDEX", "1").strip().lower() \
        not in ("0", "false", "no")


class ShardedTaskTable:
    """Task-record table partitioned into N shards, each with its own
    lock.  The dict protocol (get/[]/pop/len/items) is preserved so the
    scheduler's global-lock call sites read through unchanged; the win
    is `_op_task_events` — the highest-volume completion-drain op —
    which merges event deltas under only the record's shard lock and
    never touches the scheduler's global lock.

    items()/values()/keys() return per-shard snapshots (safe to iterate
    while other threads insert), so iteration order is shard-grouped
    rather than global insertion order — lineage pruning becomes
    approximate-oldest-first, which it already effectively was."""

    __slots__ = ("_shards", "_locks", "_n")

    def __init__(self, n: int = 8):
        self._n = max(1, n)
        self._shards: List[Dict[str, Any]] = [
            {} for _ in range(self._n)]
        self._locks = [threading.Lock() for _ in range(self._n)]

    def _idx(self, key: str) -> int:
        return hash(key) % self._n

    def lock_for(self, key: str) -> threading.Lock:
        return self._locks[self._idx(key)]

    def get(self, key, default=None):
        return self._shards[self._idx(key)].get(key, default)

    def __getitem__(self, key):
        return self._shards[self._idx(key)][key]

    def __setitem__(self, key, value):
        i = self._idx(key)
        with self._locks[i]:
            self._shards[i][key] = value

    def __delitem__(self, key):
        i = self._idx(key)
        with self._locks[i]:
            del self._shards[i][key]

    def pop(self, key, *default):
        i = self._idx(key)
        with self._locks[i]:
            return self._shards[i].pop(key, *default)

    def __contains__(self, key) -> bool:
        return key in self._shards[self._idx(key)]

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    def __bool__(self) -> bool:
        return any(self._shards)

    def items(self):
        out = []
        for i, s in enumerate(self._shards):
            with self._locks[i]:
                out.extend(s.items())
        return out

    def values(self):
        return [v for _, v in self.items()]

    def keys(self):
        return [k for k, _ in self.items()]


class PendingLeaseQueue:
    """Queued worker-lease demand, sharded by owner with incremental
    per-node / per-env / per-owner indexes.

    `_op_request_lease`'s virtual-availability view used to subtract
    queued demand by scanning EVERY pending entry per candidate node
    (O(pending x nodes) per request); the node index makes that
    O(demand actually targeting the node).  Appends are O(1); the grant
    pass rebuilds via reset() exactly where it used to rebuild the flat
    list."""

    __slots__ = ("_items", "_by_node", "_by_env", "_by_owner")

    def __init__(self):
        self._items: List[dict] = []
        self._by_node: Dict[str, List[dict]] = {}
        self._by_env: Dict[str, int] = {}
        self._by_owner: Dict[str, int] = {}

    def _index(self, pl: dict):
        nid = pl.get("node_id") or ""
        if nid:
            self._by_node.setdefault(nid, []).append(pl)
        ek = pl.get("env_key", "")
        self._by_env[ek] = self._by_env.get(ek, 0) + 1
        ow = pl.get("owner", "")
        self._by_owner[ow] = self._by_owner.get(ow, 0) + 1

    def append(self, pl: dict):
        self._items.append(pl)
        self._index(pl)

    def reset(self, items: List[dict]):
        self._items = list(items)
        self._by_node = {}
        self._by_env = {}
        self._by_owner = {}
        for pl in self._items:
            self._index(pl)

    def __iter__(self):
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def node_demand(self, node_id: str) -> List[dict]:
        return self._by_node.get(node_id, ())

    def env_count(self, env_key: str) -> int:
        return self._by_env.get(env_key, 0)

    def owners_except(self, owner_hex: str):
        return [o for o in self._by_owner if o != owner_hex]

    def earliest_deadline(self) -> Optional[float]:
        """Absolute time the soonest queued entry goes stale (spawned
        demand expires at 10s, cluster-infeasible at 15s — the grant
        pass's denial windows).  Drives the scheduler's timer-wheel arm
        instead of a fixed 0.5 s poll."""
        best = None
        for pl in self._items:
            d = pl["created"] + (10.0 if pl.get("node_id") else 15.0)
            if best is None or d < best:
                best = d
        return best


class _NodeIndex:
    """Utilization-bucketed node index + per-resource free sets: the
    O(1)-amortized candidate generator behind `_pick_node` and
    SPREAD/STRICT_SPREAD bundle placement (replacing full node-table
    scans, which made 1,000-PG create-ready collapse 3.6x on the
    2,000-node sim).

    Buckets partition [0, 1] utilization into NBUCKETS slices; each
    bucket is a list with swap-pop removal so membership updates are
    O(1) and positional probing (hash-rotated) is stable enough for
    SPREAD tie fan-out.  The index is a *candidate generator*, not an
    oracle: queries re-verify fit against the caller's (possibly
    virtual) availability view before committing, so staleness can only
    cost optimality, never correctness.  Callers `touch()` a node after
    mutating its availability; `rebuild()` runs on join/death."""

    NBUCKETS = 8

    __slots__ = ("_server", "_buckets", "_pos", "_free", "rebuilds")

    def __init__(self, server: "ControlServer"):
        self._server = server
        self._buckets: List[List[str]] = [
            [] for _ in range(self.NBUCKETS + 1)]
        # node_id -> (bucket index, position in bucket list)
        self._pos: Dict[str, tuple] = {}
        # Per-resource-class free sets: node ids with available[res]>0.
        # A scarce resource's set (e.g. TPU on a mostly-CPU cluster) is
        # tiny, so queries needing it iterate the set instead of the
        # buckets.
        self._free: Dict[str, Set[str]] = {}
        self.rebuilds = 0

    def _bucket_of(self, node) -> int:
        u = self._server._utilization(node)
        b = int(u * self.NBUCKETS)
        return min(max(b, 0), self.NBUCKETS)

    def _remove(self, node_id: str):
        at = self._pos.pop(node_id, None)
        if at is None:
            return
        b, i = at
        bucket = self._buckets[b]
        last = bucket.pop()
        if last != node_id:
            bucket[i] = last
            self._pos[last] = (b, i)

    def _insert(self, node_id: str, b: int):
        bucket = self._buckets[b]
        bucket.append(node_id)
        self._pos[node_id] = (b, len(bucket) - 1)

    def touch(self, node_id: str):
        """Re-bucket one node after its availability changed (lock
        held by the caller)."""
        node = self._server.nodes.get(node_id)
        if node is None or not node.schedulable:
            self._remove(node_id)
            for s in self._free.values():
                s.discard(node_id)
            return
        avail = node.available.to_dict()
        for res, s in self._free.items():
            if avail.get(res, 0) <= 0:
                s.discard(node_id)
        for res, v in avail.items():
            if v > 0:
                self._free.setdefault(res, set()).add(node_id)
        b = self._bucket_of(node)
        at = self._pos.get(node_id)
        if at is not None and at[0] == b:
            return
        self._remove(node_id)
        self._insert(node_id, b)

    def rebuild(self):
        """Full re-index (node join/death/drain — rare)."""
        self._buckets = [[] for _ in range(self.NBUCKETS + 1)]
        self._pos = {}
        self._free = {}
        for nid, node in self._server.nodes.items():
            if node.schedulable:
                self._insert(nid, self._bucket_of(node))
                for res, v in node.available.to_dict().items():
                    if v > 0:
                        self._free.setdefault(res, set()).add(nid)
        self.rebuilds += 1
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.record("sched", "index_rebuild",
                                   nodes=len(self._pos))
        except Exception:  # raylint: allow-swallow(telemetry only)
            pass

    def buckets_low_to_high(self):
        for b in self._buckets:
            if b:
                yield b

    def buckets_high_to_low(self, below: Optional[float] = None):
        """Buckets from most- to least-utilized; `below` drops whole
        buckets at/above that utilization (the hybrid policy's pack
        threshold)."""
        hi = len(self._buckets) - 1
        if below is not None:
            hi = min(hi, max(0, int(below * self.NBUCKETS) - 1))
        for i in range(hi, -1, -1):
            if self._buckets[i]:
                yield self._buckets[i]

    def scarce_set(self, res_names, cap: int = 16) -> Optional[Set[str]]:
        """The smallest per-resource free set among `res_names`, when
        it is small enough that iterating it beats the bucket walk;
        None when every named resource is plentiful (or unknown —
        unknown means no node has it free, returned as the empty
        set)."""
        best = None
        for r in res_names:
            s = self._free.get(r)
            if s is None:
                return set()
            if best is None or len(s) < len(best):
                best = s
        return best if best is not None and len(best) <= cap else None

    def probe(self, bucket: List[str], seed: int, accept) -> Optional[str]:
        """Rotated linear probe over one bucket: start at seed %% len
        so equal-utilization nodes fan out, return the first node
        `accept` confirms.  O(1) expected when most nodes fit."""
        n = len(bucket)
        if n == 0:
            return None
        start = seed % n
        for i in range(n):
            nid = bucket[start + i - n if start + i >= n else start + i]
            if accept(nid):
                return nid
        return None


class ControlServer:
    def __init__(self, session_id: str, config: Config, resources: ResourceSet,
                 session_dir: str, namespace: str = ""):
        self.session_id = session_id
        self.config = config
        self.session_dir = session_dir
        self.namespace = namespace
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
        from ray_tpu.core.node_manager import prewarm_zygote

        prewarm_zygote()  # worker template warms while the head boots

        self.lock = threading.RLock()
        # Object-settle condition (shares self.lock): fetch-path waiters
        # block here instead of sleep-polling; every READY/ERRORED
        # transition and restore completion notifies.
        self._obj_settled = threading.Condition(self.lock)
        # Owner-keyed submit ingress: _op_submit_task(_batch) appends
        # specs to a per-owner-shard deque WITHOUT the global lock; the
        # scheduler (and any reader that could observe an undrained
        # spec) drains them under the lock.  deque append/popleft are
        # GIL-atomic, so the ingress itself is lock-free.  None = legacy
        # single-lock ingress (RAY_TPU_GCS_SHARDS=0).
        n_shards = _gcs_shards()
        self._ingress: Optional[List[deque]] = (
            [deque() for _ in range(n_shards)] if n_shards else None)
        self._node_index = None  # built after journal restore
        self._lease_timer = None  # timer-wheel handle for lease expiry
        try:
            self._idle_wait_s = float(os.environ.get(
                "RAY_TPU_SCHED_IDLE_WAIT_S", "30.0"))
        except ValueError:
            self._idle_wait_s = 30.0
        self.objects: Dict[str, ObjectEntry] = {}
        self.workers: Dict[str, WorkerInfo] = {}
        self.actors: Dict[str, ActorEntry] = {}
        self.named_actors: Dict[tuple, str] = {}
        # Pluggable KV storage (reference gcs/store_client/, N6):
        # in-memory by default; a configured path journals to disk so
        # the KV survives head restarts.
        from ray_tpu.core.store_client import make_store_client

        self.kv = make_store_client(config.gcs_store_path)
        self.funcs: Dict[str, bytes] = {}
        # In-flight actor-task return objects: actor hex -> pending obj
        # hexes, and the reverse map. Used to fail callers' gets when an
        # actor dies with tasks in its queue (the reference fails these via
        # DirectActorTaskSubmitter::DisconnectActor).
        self.actor_inflight: Dict[str, Set[str]] = {}
        self.obj_actor: Dict[str, str] = {}
        self.tasks = ShardedTaskTable(max(1, n_shards or 8))
        # Lineage: object hex -> producing task hex, kept even after the
        # object entry itself is freed so a lost dependency can be
        # re-created (reference lineage map, task_manager.h:208).
        self.lineage: Dict[str, str] = {}
        self.pending_tasks: List[TaskSpec] = []
        # Objects some pending task waits on (ref args not yet READY):
        # lets task_done wake the scheduler only when a completion's
        # puts actually unblock someone (fast-redispatch keeps the
        # no-deps burst path pass-free).  Stale entries merely cause an
        # extra wake; pruned when the pending queue drains.
        self._dep_waiters: set = set()
        self.pending_actors: List[ActorCreationSpec] = []
        # Unsatisfied worker-lease requests (owner-direct task path):
        # granted as workers come online / free up, or denied on expiry
        # so the owner re-requests (reference: queued lease requests in
        # NodeManager::HandleRequestWorkerLease, node_manager.cc:1794).
        self.pending_leases = PendingLeaseQueue()
        # env_key -> runtime_env dict; workers fetch + apply their pool's
        # env at startup (runtime_env/plugin.py).
        self.runtime_envs: Dict[str, dict] = {}
        # env_key -> (setup error, poisoned_at); tasks needing a broken
        # env fail fast instead of respawning workers forever (reference:
        # runtime-env agent setup failure fails the lease request). The
        # poison expires so transient node-local failures (full disk, KV
        # hiccup) don't brick the env for the cluster's lifetime.
        self.broken_envs: Dict[str, tuple] = {}
        self.broken_env_ttl_s = 60.0
        # C++-defined tasks/actors (reference: cpp/include/ray/api —
        # remote functions DEFINED in C++, executed by a C++ worker
        # that registers its function/class names here).
        self.cpp_functions: Dict[str, rpc.Connection] = {}
        self.cpp_actor_classes: Dict[str, rpc.Connection] = {}
        self.cpp_instances: Dict[str, rpc.Connection] = {}
        self.cpp_inflight: Dict[int, tuple] = {}  # id(conn) -> (conn, objs)

        head = NodeState(node_id="head", total=resources,
                         available=resources, is_head=True)
        self.nodes: Dict[str, NodeState] = {"head": head}
        self.placement_groups: Dict[str, PlacementGroupEntry] = {}
        self.store = ShmObjectStore(session_id, config.shm_dir,
                                    capacity=config.object_store_memory)
        # Spilling (reference LocalObjectManager + external_storage.py):
        # cold shm objects move to external storage past the usage
        # threshold and restore transparently on next subscribe.
        from ray_tpu.core.external_storage import storage_from_spec

        self.external_storage = storage_from_spec(
            config.spill_storage, session_dir)
        self.spilled_bytes_total = 0
        # OOM defense (reference memory_monitor.h + worker killing
        # policies): kill-and-retry the newest retriable running task
        # under host memory pressure.
        self.memory_monitor = None
        if config.memory_usage_threshold > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor

            self.memory_monitor = MemoryMonitor(
                config.memory_usage_threshold,
                config.memory_monitor_refresh_s,
                on_high=self._on_memory_pressure).start()

        # Restore journaled cluster metadata (named actors, PGs, logical
        # nodes) BEFORE serving: a restarted head must know its actors
        # before their still-alive workers redial and re-announce
        # (reference: GCS restart from Redis, redis_store_client.h:33).
        # Drain bookkeeping: node_id -> object hexes whose migration to
        # a survivor arena is in flight (cleared by objects_migrated),
        # plus when the last migrate_objects batch was issued — a lost
        # report (node->head send failure) must not wedge the drain, so
        # pending entries older than the retry window re-issue
        # (completed objects answer "have" on the re-push: idempotent).
        self._drain_migrating: Dict[str, Set[str]] = {}
        self._drain_issued_at: Dict[str, float] = {}
        self._drain_retry_s = 120.0

        self._restored_actors: Set[str] = set()
        self._restore_from_journal()
        for nid in getattr(self, "_restored_drains", set()):
            node = self.nodes.get(nid)
            if node is not None:
                node.draining = True

        # O(1)-amortized node selection (RAY_TPU_NODE_INDEX=0 restores
        # the legacy full-scan policies, byte-for-byte).
        if _node_index_enabled():
            self._node_index = _NodeIndex(self)
            self._node_index.rebuild()

        # Scheduler observability (util/metrics.py): lease decisions and
        # task-event ingest volume export through the same /metrics
        # pipeline as user metrics.  frames vs events makes the delta
        # batching directly measurable (events ≫ frames under load).
        try:
            from ray_tpu.util import metrics as _m

            self._m_lease_grants = _m.Counter(
                "ray_tpu_lease_grants_total",
                "Worker leases granted by the scheduler")
            self._m_lease_denials = _m.Counter(
                "ray_tpu_lease_denials_total",
                "Lease slots requested but not granted")
            self._m_lease_clamps = _m.Counter(
                "ray_tpu_lease_fair_share_clamps_total",
                "Lease requests clamped to the per-owner fair share")
            self._m_task_events = _m.Counter(
                "ray_tpu_task_events_total",
                "Task lifecycle events ingested from workers")
            self._m_task_event_frames = _m.Counter(
                "ray_tpu_task_event_frames_total",
                "task_events frames received (events arrive batched)")
            self._m_locality_hits = _m.Counter(
                "ray_tpu_locality_hits_total",
                "Tasks placed on a node already holding >=1 shm arg")
            self._m_stragglers = _m.Counter(
                "ray_tpu_stragglers_total",
                "RUNNING tasks flagged as stragglers by the watchdog")
            self._m_node_unhealthy = _m.Counter(
                "ray_tpu_node_unhealthy_total",
                "Nodes flagged unhealthy (stale heartbeat) by the "
                "watchdog")
            self._m_shard_ops = _m.Counter(
                "ray_tpu_sched_shard_ops_total",
                "Submissions accepted through the lock-free owner-"
                "keyed ingress shards")
        except Exception:
            self._m_lease_grants = self._m_lease_denials = None
            self._m_lease_clamps = None
            self._m_task_events = self._m_task_event_frames = None
            self._m_locality_hits = None
            self._m_stragglers = self._m_node_unhealthy = None
            self._m_shard_ops = None

        # Cluster span harvest state (collect_spans wire op): per-worker
        # ring cursors persist across harvests so each pull ships only
        # new spans, and harvested spans accumulate in a bounded,
        # trace_id-indexed store the dashboard queries.
        self._span_waiters: Dict[str, tuple] = {}  # token -> (Event, slot)
        self._span_cursors: Dict[str, int] = {}  # worker_hex -> cursor
        self._span_store: "deque" = deque(
            maxlen=_env_int("RAY_TPU_SPAN_STORE_MAX", 200000, 1000))
        self._span_seen: Set[str] = set()  # span ids in _span_store
        self._span_missed = 0  # ring evictions that beat the harvest
        self._span_lock = threading.Lock()
        self._harvest_lock = threading.Lock()  # one harvest at a time
        # Latest per-worker resource samples (profile_report deltas)
        # plus a bounded per-worker history ring so /api/profile and
        # the watchdog see distributions, not just the newest sample.
        self._profiles: Dict[str, dict] = {}
        self._profile_hist: Dict[str, "deque"] = {}
        self._profile_hist_cap = _env_int("RAY_TPU_PROFILE_HISTORY",
                                          120, 8)
        # Straggler/health watchdog: constructed ONLY when enabled, so
        # with RAY_TPU_WATCHDOG off the scheduler loop's gate is a
        # single `is not None` check — today's hot path byte-for-byte.
        self._watchdog = _Watchdog(self) if _watchdog_enabled() else None
        # Durable ops plane: rehydrate the span store and flight
        # recorder from the on-disk journal (util/journal.py) so a head
        # restart still serves yesterday's trace.  No-op when
        # RAY_TPU_OPS_JOURNAL_DIR is unset.
        self._rehydrate_ops_journal()

        self._wake = threading.Event()
        self._stopped = threading.Event()
        from ray_tpu.core.wire_schema import validate as _wire_validate

        self.server = rpc.Server(self._handle, host=config.node_ip_address,
                                 port=config.control_port,
                                 on_disconnect=self._on_disconnect,
                                 json_validator=_wire_validate)
        self._sched_thread = threading.Thread(
            target=self._schedule_loop, name="scheduler", daemon=True
        )
        self._sched_thread.start()
        if self._restored_actors:
            timer = threading.Timer(config.head_restart_grace_s,
                                    self._reap_restored_actors)
            timer.daemon = True
            timer.start()

    # -- journal (reference: GCS table persistence via StoreClient) -----
    def _journal_put(self, key: str, value):
        if self.config.gcs_store_path:
            self.kv[f"__meta__/{key}"] = value

    def _journal_del(self, key: str):
        if self.config.gcs_store_path:
            self.kv.pop(f"__meta__/{key}", None)

    def _rehydrate_ops_journal(self):
        """Reload the span store and flight recorder from the durable
        ops journal after a head restart (kill -9 included: replay
        drops at most the one truncated tail record per stream).
        Replayed spans enter _span_seen, so the first post-restart
        harvest neither duplicates the store nor re-journals them."""
        from ray_tpu.util import journal as ops_journal

        directory = ops_journal.journal_dir()
        if not directory:
            return
        try:
            envs = ops_journal.replay(
                directory, "spans",
                max_records=self._span_store.maxlen or 0)
        except Exception as e:
            warn_once(logger, "ops-rehydrate", e,
                      "span journal replay failed")
            envs = []
        restored = 0
        with self._span_lock:
            for env in envs:
                row = env.get("d")
                if not isinstance(row, list) or len(row) < 7:
                    continue
                sid = row[0]
                if sid in self._span_seen:
                    continue
                if len(self._span_store) == self._span_store.maxlen \
                        and self._span_store:
                    self._span_seen.discard(self._span_store[0][0])
                self._span_seen.add(sid)
                self._span_store.append(row)
                restored += 1
        from ray_tpu.util import flight_recorder

        flight = flight_recorder.rehydrate()
        if restored or flight:
            logger.info("ops journal rehydrated: %d spans, %d flight "
                        "events (dir=%s)", restored, flight, directory)

    def _restore_from_journal(self):
        if not self.config.gcs_store_path:
            return
        if self.kv.get("__meta__/session_id") is None:
            self.kv["__meta__/session_id"] = self.session_id
            return
        # A previous head wrote this journal: restore cluster metadata.
        # Resource accounting for still-alive workers is rebuilt lazily
        # (they re-register unclaimed; transient over-subscription is
        # accepted, as in the reference's GCS-restart window).
        for key in list(self.kv):
            if key.startswith("__meta__/actor/"):
                spec = self.kv[key]
                actor_hex = spec.actor_id.hex()
                entry = ActorEntry(spec=spec, state=A_RESTARTING)
                self.actors[actor_hex] = entry
                if spec.name:
                    self.named_actors[(spec.namespace, spec.name)] = \
                        actor_hex
                self._restored_actors.add(actor_hex)
            elif key.startswith("__meta__/pg/"):
                d = self.kv[key]
                pg = PlacementGroupEntry(
                    pg_hex=key.rsplit("/", 1)[1],
                    strategy=d["strategy"],
                    bundle_specs=d["bundle_specs"],
                    name=d.get("name", ""),
                    ready_obj=d.get("ready_obj", ""))
                self.placement_groups[pg.pg_hex] = pg
                if pg.ready_obj:
                    # Re-reservation will seal it; a reconnecting
                    # driver's pg.ready() then resolves instead of
                    # hitting the restart-grace lost error.
                    self.objects.setdefault(pg.ready_obj,
                                            ObjectEntry(refcount=0))
            elif key.startswith("__meta__/drain/"):
                node_id = key.rsplit("/", 1)[1]
                self._drain_migrating.setdefault(node_id, set())
                self._restored_drains = getattr(
                    self, "_restored_drains", set())
                self._restored_drains.add(node_id)
            elif key.startswith("__meta__/node/"):
                d = self.kv[key]
                node_id = key.rsplit("/", 1)[1]
                res = ResourceSet(d["resources"])
                self.nodes[node_id] = NodeState(
                    node_id=node_id, total=res, available=res,
                    labels=d.get("labels") or {})

    def _reap_restored_actors(self):
        """Grace expired: restored actors whose worker never re-announced
        are respawned (restarts permitting) or declared dead."""
        with self.lock:
            for actor_hex in list(self._restored_actors):
                entry = self.actors.get(actor_hex)
                self._restored_actors.discard(actor_hex)
                if entry is None or entry.state != A_RESTARTING \
                        or entry.worker_hex:
                    continue
                spec = entry.spec
                if spec.restart_count < spec.max_restarts:
                    spec.restart_count += 1
                    self.pending_actors.append(spec)
                else:
                    entry.state = A_DEAD
                    entry.death_reason = \
                        "lost in head restart (no restarts left)"
                    self._push_actor_update(entry, actor_hex)
        self._wake.set()

    # ------------------------------------------------------------------
    @property
    def address(self) -> str:
        # Advertised (not bind) address: binding 0.0.0.0 must not hand
        # peers an unroutable wildcard.
        return f"{self.config.advertised_host()}:{self.server.port}"

    def stop(self):
        self._stopped.set()
        self._wake.set()
        if self._lease_timer is not None:
            self._lease_timer.cancel()
        if self.memory_monitor is not None:
            self.memory_monitor.stop()
        with self.lock:
            workers = list(self.workers.values())
            node_conns = [n.conn for n in self.nodes.values()
                          if n.conn is not None]
        for w in workers:
            if w.conn is not None and w.kind != "driver":
                try:
                    w.conn.push({"op": "exit"})
                except Exception:
                    pass
        for conn in node_conns:
            try:
                conn.push({"op": "exit"})
            except Exception:
                pass
        for client in getattr(self, "_node_clients", {}).values():
            try:
                client.close()
            except Exception:
                pass
        procs = [w.proc for w in workers if w.proc is not None]
        # Event-driven reap: block in each child's wait() against one
        # shared deadline instead of poll()+sleep spinning — the kernel
        # wakes us the instant a child exits.
        deadline = time.monotonic() + 1.0
        for p in procs:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                p.wait(max(remaining, 0.001))
            except Exception:  # raylint: allow-swallow(timeout or reaped elsewhere; stragglers escalate below)
                pass
        procs = [p for p in procs if p.poll() is None]
        for p in procs:  # stragglers: escalate
            try:
                p.kill()
            except OSError:
                pass
        self.server.stop()
        # Close the KV journal only after the server stops accepting ops
        # (an in-flight kv_put must not hit a closed file).
        try:
            self.kv.close()
        except Exception:
            pass
        self.store.cleanup()

    # ------------------------------------------------------------------
    # RPC dispatch
    def _handle(self, conn: rpc.Connection, msg: dict):
        op = msg["op"]
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown control op: {op}")
        return fn(conn, msg)

    def _on_disconnect(self, conn: rpc.Connection):
        # Stale-connection fencing: with client reconnection, a dropped
        # OLD socket must not kill an entity that has already re-bound a
        # NEW one (reference: GCS ignores failure reports from
        # superseded raylet connections).
        if conn.meta.get("cpp_worker"):
            self._cleanup_cpp_worker(conn)
        node_id = conn.meta.get("node_id")
        if node_id is not None:
            with self.lock:
                node = self.nodes.get(node_id)
                if node is None or node.conn is not conn:
                    return
            self._handle_node_death(node_id)
            return
        worker_hex = conn.meta.get("worker_hex")
        if worker_hex is None:
            return
        with self.lock:
            w = self.workers.get(worker_hex)
            if w is None or w.state == "dead" or w.conn is not conn:
                return
            self._mark_worker_dead(w, "connection lost")
        self._wake.set()
        self._sweep_store()

    def _handle_node_death(self, node_id: str):
        """A node manager's connection dropped: the host (and its arena)
        is gone.  Counterpart of GCS node-failure handling
        (gcs_node_manager.cc OnNodeFailure): fail/retry its workers'
        work, tear down its PG bundles, and recover or error every object
        whose only copy lived in its arena (lineage reconstruction,
        object_recovery_manager.h)."""
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            node.available = ResourceSet()
            node.conn = None
            self._index_touch(node_id)
            self._drop_drain_state_locked(node_id)
            for w in list(self.workers.values()):
                if w.node_id == node_id and w.state != "dead":
                    self._mark_worker_dead(w, f"node {node_id} died")
            for pg in self.placement_groups.values():
                if pg.state == "CREATED" and any(
                        b.node_id == node_id for b in pg.bundles):
                    self._teardown_pg(pg, reason=f"node {node_id} died")
            # Objects whose shm copy lived on the dead node: reconstruct
            # from lineage or materialize ObjectLostError.
            for obj_hex, entry in list(self.objects.items()):
                if entry.node_id != node_id or not entry.in_shm \
                        or entry.state != READY:
                    continue
                entry.in_shm = False
                if not self._try_reconstruct_locked(obj_hex):
                    self._store_lost_error_locked(
                        obj_hex, f"node {node_id} holding the only copy "
                        "died and lineage reconstruction was not possible")
        self._wake.set()

    def _sweep_store(self):
        """Drop shm-arena pins held by dead processes so their blocks can be
        reclaimed (plasma's client-disconnect accounting)."""
        with self.lock:
            alive = [w.pid for w in self.workers.values()
                     if w.state != "dead" and w.pid]
        alive.append(os.getpid())
        try:
            self.store.sweep(alive)
        except Exception as exc:
            # A failing sweep leaks dead workers' arena pins until the
            # store fills — keep it best-effort but never silent.
            warn_once(logger, "store-sweep", exc,
                      "shm-store sweep failed (dead-process pins leak)")

    def _mark_worker_dead(self, w: WorkerInfo, reason: str):
        """Called with lock held. Fail/retry its task, kill/restart its actor."""
        was_leased_to = w.leased_to if w.state == "leased" else ""
        w.state = "dead"
        w.conn = None
        w.leased_to = ""
        self._release(w)
        if was_leased_to:
            # Tell the lease holder so it fails over the in-flight
            # specs it owns (the head never saw them).
            owner = self.workers.get(was_leased_to)
            if owner is not None and owner.conn is not None:
                try:
                    owner.conn.push({"op": "lease_revoked",
                                     "worker": w.worker_hex,
                                     "reason": reason})
                except Exception as exc:
                    # The owner never learns its leased worker died; its
                    # in-flight specs stall until lease timeout — log so
                    # the stall has a visible cause.
                    warn_once(logger, "lease-revoke-push", exc,
                              "could not notify %s of dead leased "
                              "worker %s", was_leased_to, w.worker_hex)
        # Leases this worker HELD as an owner die with it.
        for x in self.workers.values():
            if x.state == "leased" and x.leased_to == w.worker_hex:
                self._release(x)
                x.state = "idle"
                x.leased_to = ""
        if self.pending_leases:
            self.pending_leases.reset(
                [pl for pl in self.pending_leases
                 if pl["owner"] != w.worker_hex])
        if w.current_task:
            rec = self.tasks.get(w.current_task)
            if rec is not None and rec.state == "RUNNING":
                spec = rec.spec
                if spec.direct and not spec.return_ids:
                    # Skeletal event-mirror of a lease-path task —
                    # retry/failure is the OWNER's job (lease_revoked
                    # push above); never requeue the arg-less mirror.
                    # Full direct specs (lineage-shipped, re-dispatched
                    # by reconstruction) take the normal retry path.
                    rec.state = "FAILED"
                elif spec.retry_count < spec.max_retries:
                    spec.retry_count += 1
                    rec.state = "PENDING"
                    rec.worker_hex = ""
                    self.pending_tasks.append(spec)
                else:
                    rec.state = "FAILED"
                    self._fail_task_returns_with(
                        spec, f"worker died: {reason}")
            w.current_task = None
        if w.actor_hex:
            entry = self.actors.get(w.actor_hex)
            if entry is not None and entry.state not in (A_DEAD,):
                spec = entry.spec
                # Tasks already delivered to the dead process are lost either
                # way; fail their return objects so callers' gets raise
                # instead of hanging.
                will_restart = spec.restart_count < spec.max_restarts
                if not (will_restart
                        and getattr(spec, "max_task_retries", 0) > 0):
                    self._fail_actor_inflight(w.actor_hex, reason)
                # else: the OWNER arbitrates in-flight calls across the
                # restart (runtime max_task_retries): retried calls'
                # results — and non-retried calls' errors — flow back
                # through the owner's promoted-object forwarding, so
                # the head writing ActorDiedError here would make one
                # ref read as an error remotely while the owner's
                # retry succeeds locally.  The entries stay queued; a
                # later DEAD transition fails whatever remains.
                if will_restart:
                    spec.restart_count += 1
                    entry.state = A_RESTARTING
                    entry.worker_hex = ""
                    entry.address = ""
                    self._push_actor_update(entry, w.actor_hex)
                    self.pending_actors.append(spec)
                else:
                    entry.state = A_DEAD
                    entry.death_reason = reason
                    self._push_actor_update(entry, w.actor_hex)

    def _fail_actor_inflight(self, actor_hex: str, reason: str):
        """Lock held. Store ActorDiedError into every unfinished return
        object of tasks already sent to this actor."""
        from ray_tpu.core.exceptions import ActorDiedError
        from ray_tpu.core.serialization import serialize

        pending = self.actor_inflight.pop(actor_hex, None)
        if not pending:
            return
        data = serialize(
            ActorDiedError(actor_hex, f"worker died: {reason}")).to_bytes()
        for obj_hex in list(pending):
            self.obj_actor.pop(obj_hex, None)
            entry = self.objects.get(obj_hex)
            if entry is None or entry.state == PENDING:
                self._store_object_locked(
                    obj_hex, inline=data, size=len(data), is_error=True)

    # ------------------------------------------------------------------
    # Registration
    def _op_register(self, conn, msg):
        worker_hex = msg["worker_hex"]
        with self.lock:
            w = self.workers.get(worker_hex)
            if w is None:
                # Unknown worker: either a driver, or a worker surviving
                # a head restart re-registering (it reports its node).
                w = WorkerInfo(worker_hex=worker_hex,
                               node_id=msg.get("node_id") or "head")
                self.workers[worker_hex] = w
            w.conn = conn
            w.pid = msg.get("pid", 0)
            w.address = msg.get("address", "")
            w.kind = msg.get("kind", w.kind or "pool")
            w.env_key = msg.get("env_key", w.env_key)
            conn.meta["worker_hex"] = worker_hex
            # Pool workers stay "starting" until they send worker_online
            # (hooks installed); dispatching earlier races task delivery.
            if w.kind == "driver":
                w.state = "driver"
                w.node_id = w.node_id or "head"
            # The client attaches ITS node's arena; logical nodes (and
            # the head) share the head arena.
            node = self.nodes.get(w.node_id)
            if node is not None and node.store_key:
                shm_dir = node.shm_dir or self.config.shm_dir
                store_key, store_node = node.store_key, node.node_id
            else:
                shm_dir = self.config.shm_dir
                store_key, store_node = self.session_id, "head"
        self._wake.set()
        return {
            "session_id": self.session_id,
            "shm_dir": shm_dir,
            "store_key": store_key,
            "store_node": store_node,
            "session_dir": self.session_dir,
        }

    def _op_node_stats(self, conn, msg):
        """Periodic host-stats report from a node manager's reporter
        thread (dashboard/reporter.py)."""
        with self.lock:
            for n in self.nodes.values():
                if n.conn is conn:
                    n.stats = msg.get("stats") or {}
                    n.stats_at = time.time()  # watchdog heartbeat
                    return

    def _op_register_node(self, conn, msg):
        """A node manager joins the cluster (reference raylet → GCS
        RegisterNode, gcs_service.proto NodeInfoGcsService)."""
        node_id = msg.get("node_id") or ""
        res = ResourceSet(msg["resources"])
        with self.lock:
            if not node_id:
                i = len(self.nodes)
                while f"node-{i}" in self.nodes:
                    i += 1
                node_id = f"node-{i}"
            existing = self.nodes.get(node_id)
            if existing is not None and existing.alive \
                    and existing.conn is not None:
                raise ValueError(f"node {node_id} already exists")
            # Dead (or restart-orphaned) node ids may be revived: the
            # manager reconnecting after a head restart keeps its
            # identity, arena and workers.
            self.nodes[node_id] = NodeState(
                node_id=node_id, total=res, available=res,
                labels=msg.get("labels") or {},
                address=msg.get("address", ""), conn=conn,
                store_key=msg.get("store_key", ""),
                shm_dir=msg.get("shm_dir", ""))
            self._index_touch(node_id)
            conn.meta["node_id"] = node_id
        # Force a view broadcast so the (re)joining manager gets the
        # current resource view even when nothing else changed.
        self._view_last = None
        self._wake.set()
        return {"node_id": node_id, "session_id": self.session_id,
                "namespace": self.namespace}

    def _op_worker_spawn_failed(self, conn, msg):
        """A node manager could not start a requested worker process."""
        with self.lock:
            w = self.workers.get(msg.get("worker_hex", ""))
            if w is not None and w.state != "dead":
                self._mark_worker_dead(
                    w, f"spawn failed: {msg.get('error', 'unknown')}")
        self._wake.set()

    # ------------------------------------------------------------------
    # Objects
    def _store_object_locked(self, obj_hex: str, *, inline, size, is_error,
                             in_shm: bool = False, node_id: str = "head"):
        entry = self.objects.get(obj_hex)
        if entry is None:
            entry = self.objects[obj_hex] = ObjectEntry()
        entry.state = ERRORED if is_error else READY
        entry.inline = inline
        entry.size = size
        entry.in_shm = in_shm
        entry.node_id = node_id if in_shm else "head"
        entry.is_error = is_error
        entry.stored_at = time.time()
        # Wake fetch-path waiters parked in _await_object_settled (the
        # condition shares self.lock, which is held here).
        self._obj_settled.notify_all()
        actor_hex = self.obj_actor.pop(obj_hex, None)
        if actor_hex is not None:
            self.actor_inflight.get(actor_hex, set()).discard(obj_hex)
        subs, entry.subscribers = entry.subscribers, []
        push = self._object_ready_msg(obj_hex, entry)
        for c in subs:
            try:
                c.push(push)
            except Exception as exc:
                # A lost object_ready leaves that subscriber's get()
                # blocked until timeout — worth a (rate-limited) trace.
                warn_once(logger, "object-ready-push", exc,
                          "could not push object_ready for %s to a "
                          "subscriber", obj_hex)
        # A dropped generator's free may have arrived before this EOS
        # put: apply it now that the stream is provably finished.
        frees = getattr(self, "_pending_stream_frees", None)
        if frees:
            parked = frees.pop(obj_hex, None)
            if parked is not None:
                threading.Thread(
                    target=self._op_free_stream, args=(None, parked),
                    name="stream-free", daemon=True).start()

    def _object_ready_msg(self, obj_hex, entry):
        # Location info lets clients on OTHER nodes pull the bytes from
        # the holding node's manager ("addr"); addr == "" means the copy
        # is in the head arena (fetch rides the control connection).
        addr = ""
        if entry.in_shm and entry.node_id != "head":
            node = self.nodes.get(entry.node_id)
            addr = node.address if node is not None else ""
        return {
            "op": "object_ready",
            "obj": obj_hex,
            "size": entry.size,
            "inline": entry.inline,
            "in_shm": entry.in_shm,
            "is_error": entry.is_error,
            "node": entry.node_id,
            "addr": addr,
        }

    def _store_node_for(self, conn) -> str:
        """Lock held. Which node's arena a connection's shm puts land in."""
        worker_hex = conn.meta.get("worker_hex")
        w = self.workers.get(worker_hex) if worker_hex else None
        if w is None:
            return "head"
        node = self.nodes.get(w.node_id)
        return node.node_id if node is not None and node.store_key \
            else "head"

    def _op_put_object_batch(self, conn, msg):
        """A run of consecutive puts from one owner, registered under ONE
        lock hold with one spill check and at most one scheduler wake
        (the put-heavy loops in ray_perf made per-put head work the
        dominant cost)."""
        any_shm = False
        with self.lock:
            for item in msg["items"]:
                self._put_object_locked(conn, item)
                any_shm = any_shm or bool(item.get("in_shm"))
        if any_shm:
            self._maybe_spill()
        if self.pending_tasks or self.pending_leases \
                or self._ingress_pending():
            self._wake.set()

    def _op_put_object(self, conn, msg):
        with self.lock:
            self._put_object_locked(conn, msg)
        if msg.get("in_shm"):
            # Outside the lock: spilling does storage I/O that must not
            # stall the control plane.
            self._maybe_spill()
        # Wake the scheduler only when something could be waiting on the
        # arrival (a put with no queued work has nothing to unblock; the
        # loop's timeout covers stragglers).
        if self.pending_tasks or self.pending_leases \
                or self._ingress_pending():
            self._wake.set()

    def _put_object_locked(self, conn, msg):
        """Lock held (both callers)."""
        spec = msg.get("lineage")
        if spec is not None:
            # Owner-side lineage shipped with the object (lease-path
            # tasks whose oversized result lands in shm: the head
            # never saw the spec, but must be able to re-execute it
            # if the copy is lost — reference: owner-held lineage,
            # task_manager.h:208).
            task_hex = spec.task_id.hex()
            existing = self.tasks.get(task_hex)
            if existing is None or not existing.spec.return_ids:
                # Replace the skeletal event-mirror record (if any):
                # only the full spec can be re-executed.
                self.tasks[task_hex] = TaskRecord(
                    spec=spec, state="FINISHED",
                    submitted_at=time.time(),
                    finished_at=time.time())
            self.lineage[msg["obj"]] = task_hex
        self._store_object_locked(
            msg["obj"],
            inline=msg.get("inline"),
            size=msg["size"],
            is_error=msg.get("is_error", False),
            in_shm=msg.get("in_shm", False),
            node_id=self._store_node_for(conn),
        )

    # -- spilling ------------------------------------------------------
    def _maybe_spill(self):
        """Spill oldest cold shm objects until under the threshold
        (reference LocalObjectManager::SpillObjectsOfSize). Candidate
        snapshot under the lock; reads/uploads outside it; per-object
        finalize re-checks the entry (it may have been freed/raced)."""
        thresh = self.config.object_spilling_threshold
        cap, used, _, _ = self.store.stats()
        if thresh <= 0 or cap <= 0 or used <= thresh * cap:
            return
        target = int(thresh * cap * 0.9)  # hysteresis below the threshold
        with self.lock:
            now = time.time()
            candidates = sorted(
                ((h, e.size, e.stored_at)
                 for h, e in self.objects.items()
                 if e.state == READY and e.in_shm
                 and e.node_id == "head"  # only the head reads its arena
                 and e.spilled_uri is None and not e.restoring
                 and now - e.stored_at >= self.config.spill_min_age_s),
                key=lambda t: t[2])
        for obj_hex, size, _ in candidates:
            if used <= target:
                break
            oid = ObjectID.from_hex(obj_hex)
            try:
                seg = self.store.attach(oid, size)
                data = bytes(seg.buf[:size])
                self.store.release(oid)
                # Unique key per spill ATTEMPT: concurrent spillers of the
                # same object must not share a URI, or the loser's stale
                # cleanup would unlink the winner's (only) copy.
                uri = self.external_storage.spill(
                    f"{obj_hex}-{uuid.uuid4().hex[:8]}", data)
            except Exception as exc:
                # Spill failures loop forever against a full arena; the
                # operator needs to see WHY eviction is making no room.
                warn_once(logger, "spill", exc,
                          "could not spill object %s (arena stays full)",
                          obj_hex)
                continue
            with self.lock:
                entry = self.objects.get(obj_hex)
                if entry is None or not entry.in_shm \
                        or entry.state != READY or entry.restoring:
                    stale = True  # freed or changed while we spilled
                else:
                    stale = False
                    entry.in_shm = False
                    entry.spilled_uri = uri
                    self.spilled_bytes_total += size
            if stale:
                try:
                    self.external_storage.delete(uri)
                except Exception as exc:
                    warn_once(logger, "spill-cleanup", exc,
                              "could not delete stale spill %s "
                              "(external storage leaks)", uri)
                continue
            # Readers that attached before this keep valid views (the
            # arena orphans pinned blocks); late readers restore.
            self.store.delete(oid)
            used -= size

    def _restore_and_publish(self, obj_hex: str):
        """Background restore of a spilled object: storage I/O happens
        off the control-plane lock; subscribers get the ready push (or a
        serialized error) when it lands."""
        with self.lock:
            entry = self.objects.get(obj_hex)
            if entry is None or entry.spilled_uri is None \
                    or entry.restoring:
                return
            entry.restoring = True
            uri = entry.spilled_uri
        data, err = None, None
        try:
            data = self.external_storage.restore(uri)
        except Exception as e:  # noqa: BLE001
            err = e
        if data is not None:
            try:
                oid = ObjectID.from_hex(obj_hex)
                seg = self.store.create(oid, len(data))
                seg.buf[:len(data)] = data
                self.store.seal(oid)
            except Exception as e:  # noqa: BLE001
                err, data = e, None
        with self.lock:
            entry = self.objects.get(obj_hex)
            if entry is None:
                return
            entry.restoring = False
            self._obj_settled.notify_all()
            if data is None:
                # The spilled copy is gone: fall back to lineage
                # reconstruction; queued subscribers stay on the entry and
                # resolve when the re-executed task stores the value.
                entry.spilled_uri = None
                if not self._try_reconstruct_locked(obj_hex):
                    # Store a REAL serialized error (not just a push):
                    # current waiters raise it now and later gets see the
                    # same ObjectLostError instead of a payload-less READY.
                    self._store_lost_error_locked(
                        obj_hex, f"restore of spilled copy failed ({err}) "
                        "and lineage reconstruction was not possible")
                return
            subs, entry.subscribers = entry.subscribers, []
            entry.spilled_uri = None
            entry.in_shm = True
            entry.node_id = "head"  # restored into the head arena
            entry.stored_at = time.time()
            push = self._object_ready_msg(obj_hex, entry)
        for c in subs:
            try:
                c.push(push)
            except Exception:
                pass
        if data is not None:
            try:
                self.external_storage.delete(uri)
            except Exception:
                pass

    # -- lineage reconstruction ----------------------------------------
    def _shm_value_lost(self, obj_hex: str, entry: ObjectEntry) -> bool:
        """Lock held. True for a READY shm-backed object whose arena
        segment is gone with no spilled copy: the value itself is lost."""
        if not (entry.state == READY and entry.in_shm
                and entry.inline is None and entry.spilled_uri is None
                and not entry.restoring):
            return False
        if entry.node_id != "head":
            # Remote-node arena: the head can't probe it; the copy is
            # lost exactly when its node is (node death already triggers
            # reconstruction eagerly in _handle_node_death).
            node = self.nodes.get(entry.node_id)
            return node is None or not node.alive
        return not self.store.contains(ObjectID.from_hex(obj_hex))

    def _try_reconstruct_locked(self, obj_hex: str) -> bool:
        """Lock held. Re-execute the task that produced a lost object
        (reference ObjectRecoveryManager::RecoverObject,
        core_worker/object_recovery_manager.h, + TaskManager lineage
        resubmission, task_manager.h:208), recursively re-creating lost
        dependencies first. Plans the full dependency tree before
        mutating anything, so an unrecoverable dep deep in the chain
        can't leave earlier deps pointlessly re-executing.

        Returns True when the entry has been reset to PENDING and its
        producing task queued (or already in flight); subscribers then
        resolve through the normal object_ready push when the
        re-execution stores the value."""
        plan: List[tuple] = []  # (obj_hex, task_hex, resubmit)
        if not self._plan_reconstruct_locked(obj_hex, plan, set()):
            return False
        requeued: Set[str] = set()
        for o_hex, task_hex, resubmit in plan:
            entry = self.objects.get(o_hex)
            if entry is None:
                entry = self.objects[o_hex] = ObjectEntry(
                    refcount=0, producing_task=task_hex)
            entry.reconstructions += 1
            entry.state = PENDING
            entry.inline = None
            entry.in_shm = False
            entry.spilled_uri = None
            entry.is_error = False
            if resubmit and task_hex not in requeued:
                requeued.add(task_hex)
                rec = self.tasks[task_hex]
                spec = rec.spec
                # Completing the re-run decrefs the task's borrows again
                # (worker.py batches decrefs into task_done);
                # pre-compensate so the double decref can't free
                # arguments early.
                for b in spec.borrows:
                    dep = self.objects.get(b)
                    if dep is not None:
                        dep.refcount += 1
                spec.retry_count = 0
                rec.state = "PENDING"
                rec.worker_hex = ""
                self.pending_tasks.append(spec)
        if requeued:
            self._wake.set()
        return True

    def _plan_reconstruct_locked(self, obj_hex: str, plan: List[tuple],
                                 seen: Set[str]) -> bool:
        """Lock held. Validate that obj_hex (and every lost dependency
        under it) is recoverable, appending (obj, task, resubmit) steps
        to ``plan`` in dependency-first order. No mutation."""
        if not self.config.enable_object_reconstruction:
            return False
        if obj_hex in seen:
            # Already planned along another path (duplicate arg /
            # diamond dependency). Object IDs form a DAG, so a revisit
            # can't be a cycle; a node that failed validation aborts the
            # whole plan before any revisit could happen.
            return True
        seen.add(obj_hex)
        task_hex = self.lineage.get(obj_hex)
        rec = self.tasks.get(task_hex) if task_hex else None
        if rec is None:
            return False
        spec = rec.spec
        # Actor-method results depend on actor state and streaming items
        # on consumed generators; neither re-executes deterministically
        # (the reference likewise only reconstructs normal task returns).
        if spec.actor_id is not None or spec.is_streaming:
            return False
        entry = self.objects.get(obj_hex)
        if entry is not None and entry.reconstructions >= \
                self.config.object_reconstruction_max_attempts:
            return False
        resubmit = rec.state not in ("PENDING", "RUNNING")
        if resubmit:
            # Lost dependencies must be re-created first; the scheduler
            # then holds this task until they are READY (_deps_ready).
            for arg in spec.args:
                if not arg.is_ref:
                    continue
                dep = self.objects.get(arg.object_hex)
                if dep is None or self._shm_value_lost(arg.object_hex,
                                                       dep):
                    if not self._plan_reconstruct_locked(
                            arg.object_hex, plan, seen):
                        return False
        plan.append((obj_hex, task_hex, resubmit))
        return True

    def _store_lost_error_locked(self, obj_hex: str, why: str):
        """Lock held. Store + publish a serialized ObjectLostError as the
        object's value so pending and future gets raise it."""
        from ray_tpu.core.serialization import serialize

        payload = serialize(ObjectLostError(
            f"object {obj_hex} is lost: {why}")).to_bytes()
        self._store_object_locked(
            obj_hex, inline=payload, size=len(payload), is_error=True)

    def _prune_lineage_locked(self):
        """Lock held. Evict the oldest finished task records (and their
        return objects' lineage links) past the retention cap, bounding
        control-plane memory on long-running drivers (reference: lineage
        eviction under max_lineage_bytes + GcsTaskManager's
        task_events_max_num_task_in_gcs cap)."""
        cap = self.config.max_lineage_entries
        if cap <= 0 or len(self.tasks) <= cap:
            return
        target = (cap * 3) // 4
        drop = []
        excess = len(self.tasks) - target
        for task_hex, rec in self.tasks.items():
            if len(drop) >= excess:
                break
            if rec.state in ("FINISHED", "FAILED"):
                drop.append(task_hex)
        for task_hex in drop:
            rec = self.tasks.pop(task_hex)
            for oid in rec.spec.return_ids:
                self.lineage.pop(oid.hex(), None)

    # -- OOM defense ---------------------------------------------------
    def _on_memory_pressure(self, fraction: float):
        from ray_tpu.core.memory_monitor import pick_worker_to_kill

        # Cooldown: give the previous kill's reclaim time to land before
        # considering another, or a single spike cascades through the
        # whole pool.
        now = time.time()
        if now - getattr(self, "_last_oom_kill", 0.0) \
                < self.config.oom_kill_cooldown_s:
            return
        with self.lock:
            candidates = []
            for w in self.workers.values():
                # "leased" workers' running tasks are known via their
                # batched RUNNING events (_op_task_events).
                if w.state not in ("busy", "leased") or not w.current_task:
                    continue
                if w.proc is None:
                    # Remote-node worker: its pid belongs to another host
                    # (killing it locally would hit an unrelated process),
                    # and the pressure being relieved is THIS host's.
                    continue
                rec = self.tasks.get(w.current_task)
                if rec is None:
                    continue
                candidates.append({
                    "worker": w,
                    "retriable":
                        rec.spec.retry_count < rec.spec.max_retries,
                    "started_at": rec.started_at,
                })
            pick = pick_worker_to_kill(
                candidates,
                allow_nonretriable=(
                    fraction
                    >= self.config.memory_usage_threshold_critical))
        if pick is None:
            return
        self._last_oom_kill = now
        w = pick["worker"]
        try:
            os.kill(w.pid, 9)  # _mark_worker_dead retries the task
        except (ProcessLookupError, PermissionError):
            pass

    def _op_subscribe_objects(self, conn, msg):
        """Batched subscribe (one message for a whole get())."""
        for obj_hex in msg["objs"]:
            self._op_subscribe_object(
                conn, {"obj": obj_hex, "grace": msg.get("grace", False)})

    def _schedule_object_grace(self, obj_hex: str):
        """A post-restart re-subscribe referenced an object this head
        doesn't know.  Its producer may still be running (result lands
        via task_done puts) — give it a grace window, then fail the
        object so gets surface an error instead of hanging (the
        'resubmitted or surfaced as errors' half of restart FT).
        Lock held.  ONE shared timer sweeps the whole graced set — a
        big fan-out's re-subscribe batch must not spawn a thread per
        object."""
        graced = getattr(self, "_graced_objects", None)
        if graced is None:
            graced = self._graced_objects = set()
        graced.add(obj_hex)
        timer = getattr(self, "_grace_timer", None)
        if timer is None or not timer.is_alive():
            timer = threading.Timer(self.config.head_restart_grace_s,
                                    self._expire_graced_objects)
            timer.daemon = True
            self._grace_timer = timer
            timer.start()

    def _expire_graced_objects(self):
        with self.lock:
            graced = getattr(self, "_graced_objects", set())
            self._graced_objects = set()
            for obj_hex in graced:
                entry = self.objects.get(obj_hex)
                if entry is not None and entry.state == PENDING:
                    self._store_lost_error_locked(
                        obj_hex, "lost in head restart (no producer "
                        "re-reported it within the grace window)")

    def _op_subscribe_object(self, conn, msg):
        obj_hex = msg["obj"]
        with self.lock:
            entry = self._object_entry_or_drain_locked(obj_hex)
            if entry is None:
                entry = self.objects[obj_hex] = ObjectEntry(refcount=0)
                if msg.get("grace"):
                    self._schedule_object_grace(obj_hex)
            if entry.state in (READY, ERRORED):
                if entry.spilled_uri is not None or entry.restoring:
                    # Spilled: queue the subscriber and restore in the
                    # background (storage I/O must not hold self.lock).
                    # An in-flight restore publishes to the whole queue,
                    # so only the first subscriber spawns the thread.
                    entry.subscribers.append(conn)
                    if not entry.restoring:
                        threading.Thread(
                            target=self._restore_and_publish,
                            args=(obj_hex,), daemon=True,
                            name=f"restore-{obj_hex[:8]}").start()
                elif self._shm_value_lost(obj_hex, entry):
                    # Only copy vanished from the arena (swept orphan,
                    # external deletion): reconstruct from lineage; the
                    # subscriber resolves when the re-run stores it.
                    entry.subscribers.append(conn)
                    if not self._try_reconstruct_locked(obj_hex):
                        self._store_lost_error_locked(
                            obj_hex, "shm copy gone and lineage "
                            "reconstruction not possible")
                else:
                    conn.push(self._object_ready_msg(obj_hex, entry))
            else:
                entry.subscribers.append(conn)

    def _op_object_info(self, conn, msg):
        """Synchronous location/size lookup for a READY object (the
        push-broadcast path, core/object_plane.py, needs size +
        shm-residency without a subscription round trip)."""
        with self.lock:
            entry = self.objects.get(msg["obj"])
            if entry is None or entry.state != READY:
                return None
            info = self._object_ready_msg(msg["obj"], entry)
        info.pop("op", None)
        return info

    def _op_forget_object(self, conn, msg):
        """Drop a speculative PENDING entry created by a subscribe that
        will never resolve (stream item probes past the final index)."""
        with self.lock:
            entry = self.objects.get(msg["obj"])
            if entry is None:
                return
            entry.subscribers = [c for c in entry.subscribers
                                 if c is not conn]
            if entry.state == PENDING and entry.refcount <= 0 \
                    and not entry.subscribers \
                    and entry.producing_task is None:
                del self.objects[msg["obj"]]

    def _op_incref(self, conn, msg):
        with self.lock:
            entry = self._object_entry_or_drain_locked(msg["obj"])
            if entry is not None:
                entry.refcount += msg.get("n", 1)

    def _op_incref_batch(self, conn, msg):
        with self.lock:
            for obj_hex in msg["objs"]:
                entry = self._object_entry_or_drain_locked(obj_hex)
                if entry is not None:
                    entry.refcount += 1

    def _op_decref_batch(self, conn, msg):
        for obj_hex in msg["objs"]:
            self._op_decref(conn, {"obj": obj_hex})

    def _op_refcount_delta(self, conn, msg):
        """Net per-object ref-count deltas, coalesced client-side from
        an adjacent incref/decref run (runtime._head_frames): positive
        entries are plain increfs, negative ones go through the decref
        path so free-on-zero (shm/spill cleanup) still fires."""
        decrefs = []
        with self.lock:
            for obj_hex, d in msg["deltas"].items():
                d = int(d)
                if d > 0:
                    entry = self._object_entry_or_drain_locked(obj_hex)
                    if entry is not None:
                        entry.refcount += d
                elif d < 0:
                    decrefs.append((obj_hex, -d))
        for obj_hex, n in decrefs:
            self._op_decref(conn, {"obj": obj_hex, "n": n})

    def _op_decref(self, conn, msg):
        to_delete = []
        with self.lock:
            obj_hex = msg["obj"]
            entry = self._object_entry_or_drain_locked(obj_hex)
            if entry is None:
                return
            entry.refcount -= msg.get("n", 1)
            if entry.refcount <= 0 and entry.state in (READY, ERRORED):
                del self.objects[obj_hex]
                if entry.in_shm:
                    for loc in {entry.node_id, *entry.replicas}:
                        to_delete.append((obj_hex, loc))
                if entry.spilled_uri:
                    try:
                        self.external_storage.delete(entry.spilled_uri)
                    except Exception as exc:
                        warn_once(logger, "spill-cleanup", exc,
                                  "could not delete spill %s for freed "
                                  "object (external storage leaks)",
                                  entry.spilled_uri)
        for obj_hex, node_loc in to_delete:
            self._delete_shm_copy(obj_hex, node_loc)

    def _delete_shm_copy(self, obj_hex: str, node_loc: str):
        """Free an object's arena copy wherever it lives: the head's
        store directly, or a delete push to the holding node's manager
        (remote arenas would otherwise fill with freed garbage)."""
        if node_loc == "head":
            self.store.delete(ObjectID.from_hex(obj_hex))
            return
        with self.lock:
            cached = getattr(self, "_proxy_cache", None)
            if cached is not None and cached[0] == obj_hex:
                self._proxy_cache = None
            node = self.nodes.get(node_loc)
            conn = node.conn if node is not None and node.alive else None
        if conn is not None:
            try:
                conn.push({"op": "delete_object", "obj": obj_hex})
            except Exception as exc:
                # The remote arena keeps the freed copy until that node
                # restarts — a slow remote leak worth one warning.
                warn_once(logger, "delete-push", exc,
                          "could not push delete_object %s to node %s",
                          obj_hex, node_loc)

    def _op_object_replica(self, conn, msg):
        """A client cached a pulled copy in its node's arena: record the
        location so freeing the object deletes every copy."""
        with self.lock:
            entry = self.objects.get(msg["obj"])
            if entry is None:
                return
            node = self._store_node_for(conn)
            entry.pulling.pop(node, None)  # in-flight pull landed
            if node != entry.node_id:
                entry.replicas.add(node)

    def _op_object_pull_started(self, conn, msg):
        """One-way announce from a PullManager leader: this node is
        pulling the object.  The locality tie-break credits in-flight
        destinations too (ROADMAP PR 3 follow-up) so a task chasing the
        object lands where it is about to be, instead of triggering a
        second transfer.  Entries are timestamps — _locality_bytes
        ignores announcements older than the pull timeout (the pull
        failed or the announce outlived its object)."""
        with self.lock:
            entry = self.objects.get(msg["obj"])
            if entry is None:
                return
            node = self._store_node_for(conn)
            if node != entry.node_id and node not in entry.replicas:
                entry.pulling[node] = time.time()

    def _op_register_objects(self, conn, msg):
        """Pre-register return objects of direct (actor) tasks with one ref
        held by the submitter, mirroring TaskManager::AddPendingTask return
        registration (reference core_worker.cc:2231).  When tied to an
        actor, track them so actor death fails outstanding callers."""
        actor_hex = msg.get("actor")
        with self.lock:
            for obj_hex in msg["objs"]:
                self.objects.setdefault(obj_hex, ObjectEntry())
                if actor_hex:
                    self.actor_inflight.setdefault(
                        actor_hex, set()).add(obj_hex)
                    self.obj_actor[obj_hex] = actor_hex

    def _op_free_objects(self, conn, msg):
        to_delete = []
        with self.lock:
            for obj_hex in msg["objs"]:
                # Explicit free forfeits reconstruction (the reference
                # likewise deletes lineage on ray.internal.free).
                self.lineage.pop(obj_hex, None)
                entry = self.objects.pop(obj_hex, None)
                if entry is not None and entry.in_shm:
                    for loc in {entry.node_id, *entry.replicas}:
                        to_delete.append((obj_hex, loc))
                if entry is not None and entry.spilled_uri:
                    try:
                        self.external_storage.delete(entry.spilled_uri)
                    except Exception:
                        pass
        for obj_hex, node_loc in to_delete:
            self._delete_shm_copy(obj_hex, node_loc)

    # ------------------------------------------------------------------
    # Functions (counterpart of _private/function_manager.py export tables)
    def _op_put_func(self, conn, msg):
        with self.lock:
            self.funcs.setdefault(msg["func_id"], msg["blob"])
            # Persistent-KV mode also journals the blob so named
            # functions remain invokable after a head restart.
            if self.config.gcs_store_path:
                key = f"__fn_blob__/{msg['func_id']}"
                if key not in self.kv:
                    self.kv[key] = msg["blob"]

    def _op_get_func(self, conn, msg):
        with self.lock:
            blob = self.funcs.get(msg["func_id"])
            if blob is None:
                blob = self.kv.get(f"__fn_blob__/{msg['func_id']}")
            return blob

    # ------------------------------------------------------------------
    # KV store (reference: gcs_kv_manager / experimental/internal_kv.py)
    # Internal-only namespaces: persisted function BLOBS are executed as
    # code on workers and __meta__/ holds journaled cluster state, so
    # user-facing KV ops must not be able to write or delete them (a
    # kv_put there would be code injection across a head restart).
    _KV_RESERVED = ("__fn_blob__/", "__meta__/")

    def _op_kv_put(self, conn, msg):
        key = msg["key"]
        if key.startswith(self._KV_RESERVED):
            raise ValueError(f"key prefix {self._KV_RESERVED!r} is "
                             "reserved for the control plane")
        with self.lock:
            if msg.get("overwrite", True) or key not in self.kv:
                self.kv[key] = msg["value"]
                return True
            return False

    def _op_kv_get(self, conn, msg):
        with self.lock:
            return self.kv.get(msg["key"])

    def _op_kv_del(self, conn, msg):
        if msg["key"].startswith(self._KV_RESERVED):
            raise ValueError(f"key prefix {self._KV_RESERVED!r} is "
                             "reserved for the control plane")
        with self.lock:
            return self.kv.pop(msg["key"], None) is not None

    def _op_kv_keys(self, conn, msg):
        prefix = msg.get("prefix", "")
        with self.lock:
            return [k for k in self.kv if k.startswith(prefix)
                    and not k.startswith(self._KV_RESERVED)]

    def _op_kv_exists(self, conn, msg):
        with self.lock:
            return msg["key"] in self.kv

    # ------------------------------------------------------------------
    # Tasks
    def _enqueue_task_locked(self, spec: TaskSpec, now: float):
        for oid in spec.return_ids:
            self.objects.setdefault(oid.hex(), ObjectEntry(
                producing_task=spec.task_id.hex()))
            self.lineage[oid.hex()] = spec.task_id.hex()
        for arg in spec.args:
            if arg.is_ref:
                entry = self.objects.get(arg.object_hex)
                if entry is None or entry.state == PENDING:
                    self._dep_waiters.add(arg.object_hex)
        self.tasks[spec.task_id.hex()] = TaskRecord(
            spec=spec, submitted_at=now)
        self.pending_tasks.append(spec)

    def _ingress_pending(self) -> bool:
        """Any submitted-but-undrained specs in the ingress shards?
        deque truthiness is GIL-atomic, so this is safe lock-free."""
        ing = self._ingress
        return ing is not None and any(ing)

    def _ingress_shard_of(self, spec) -> int:
        # Owner id keys the shard so one owner's submissions stay FIFO
        # (a shard deque preserves per-producer order) while different
        # owners never touch the same deque entry.
        owner = getattr(spec, "owner", "") or ""
        return hash(owner) % len(self._ingress)

    def _drain_submit_ingress_locked(self):
        """Lock held.  Move every staged spec into the real pending
        queue/table.  Amortized O(1) per task (each spec is drained
        exactly once); the empty check is a handful of GIL-atomic deque
        reads."""
        ing = self._ingress
        if ing is None:
            return
        drained = 0
        for shard in ing:
            while True:
                try:
                    spec, ts = shard.popleft()
                except IndexError:
                    break
                self._enqueue_task_locked(spec, ts)
                drained += 1
        if drained:
            try:
                from ray_tpu.util import flight_recorder

                flight_recorder.record("sched", "shard_dispatch",
                                       n=drained)
            except Exception:  # raylint: allow-swallow(telemetry only)
                pass

    def _object_entry_or_drain_locked(self, obj_hex: str):
        """Lock held.  Object-directory lookup that tolerates ingress
        deferral: a ref-counting / subscribe op can arrive (from a
        DIFFERENT owner's connection) before the submit that registers
        the object's entry has drained — without the drain-on-miss an
        incref would silently no-op and the ref later double-free."""
        entry = self.objects.get(obj_hex)
        if entry is None and self._ingress_pending():
            self._drain_submit_ingress_locked()
            entry = self.objects.get(obj_hex)
        return entry

    def _op_submit_task(self, conn, msg):
        spec = msg["spec"]
        if self._ingress is not None:
            self._ingress[self._ingress_shard_of(spec)].append(
                (spec, time.time()))
            if self._m_shard_ops is not None:
                try:
                    self._m_shard_ops.inc()
                except Exception:  # raylint: allow-swallow(telemetry only)
                    pass
        else:
            with self.lock:
                self._enqueue_task_locked(spec, time.time())
        self._wake.set()

    def _op_submit_task_batch(self, conn, msg):
        """Coalesced submission (runtime.py _queue_for_flush): one frame
        for a whole burst of tasks.  With ingress shards enabled the
        burst is staged lock-free on the owner's shard and drained by
        the scheduler; submission no longer contends with dispatch or
        completion on the global lock."""
        now = time.time()
        specs = msg["specs"]
        if self._ingress is not None and specs:
            shard = self._ingress[self._ingress_shard_of(specs[0])]
            for spec in specs:
                shard.append((spec, now))
            if self._m_shard_ops is not None:
                try:
                    self._m_shard_ops.inc(len(specs))
                except Exception:  # raylint: allow-swallow(telemetry only)
                    pass
        else:
            with self.lock:
                for spec in specs:
                    self._enqueue_task_locked(spec, now)
        self._wake.set()

    # -- C++-defined tasks/actors ---------------------------------------
    # Reference: cpp/include/ray/api/*.h lets users DEFINE remote
    # functions and actors in C++; a C++ worker process registers its
    # function/class names and executes pushed calls
    # (cpp/include/ray_tpu/worker.h speaks this protocol).
    def _op_register_cpp_functions(self, conn, msg):
        with self.lock:
            conn.meta["cpp_worker"] = True
            for name in msg.get("functions", ()):
                self.cpp_functions[name] = conn
            for name in msg.get("actor_classes", ()):
                self.cpp_actor_classes[name] = conn
        return {"registered": True}

    def _submit_cpp_call(self, target: rpc.Connection, what: dict,
                         args) -> str:
        """Create the return object and push the call to the C++ worker
        (JSON one-way frame); returns the return object hex."""
        return_id = ObjectID.from_random().hex()
        with self.lock:
            self.objects.setdefault(return_id, ObjectEntry())
            self.cpp_inflight.setdefault(
                id(target), (target, set()))[1].add(return_id)
        try:
            target.push_json({"op": "execute_cpp_task", **what,
                              "args": list(args or ()),
                              "return": return_id})
        except Exception as e:  # worker gone mid-call
            self._fail_cpp_return(return_id, f"cpp worker unreachable: {e}")
        return return_id

    def _fail_cpp_return(self, obj_hex: str, reason: str):
        from ray_tpu.core.serialization import serialize

        data = serialize(RuntimeError(reason)).to_bytes()
        with self.lock:
            entry = self.objects.get(obj_hex)
            if entry is None or entry.state == PENDING:
                self._store_object_locked(
                    obj_hex, inline=data, size=len(data), is_error=True)

    def _cleanup_cpp_worker(self, conn):
        """The C++ worker's connection dropped: unregister its names,
        fail its in-flight calls, drop its actor instances."""
        with self.lock:
            self.cpp_functions = {
                k: v for k, v in self.cpp_functions.items() if v is not conn}
            self.cpp_actor_classes = {
                k: v for k, v in self.cpp_actor_classes.items()
                if v is not conn}
            self.cpp_instances = {
                k: v for k, v in self.cpp_instances.items() if v is not conn}
            _, objs = self.cpp_inflight.pop(id(conn), (None, set()))
        for obj_hex in objs:
            self._fail_cpp_return(obj_hex, "cpp worker died")

    def _op_cpp_task_done(self, conn, msg):
        from ray_tpu.core.serialization import serialize

        obj_hex = msg["return"]
        err = msg.get("error")
        value = (RuntimeError(f"cpp task failed: {err}") if err
                 else msg.get("result"))
        data = serialize(value).to_bytes()
        with self.lock:
            ent = self.cpp_inflight.get(id(conn))
            if ent is not None:
                ent[1].discard(obj_hex)
            self._store_object_locked(
                obj_hex, inline=data, size=len(data),
                is_error=bool(err))
        return True

    def _op_list_cpp_functions(self, conn, msg):
        with self.lock:
            return sorted(self.cpp_functions)

    def _op_create_cpp_actor(self, conn, msg):
        cls = msg["actor_class"]
        with self.lock:
            target = self.cpp_actor_classes.get(cls)
        if target is None:
            raise ValueError(f"no C++ actor class registered as {cls!r}")
        import uuid as _uuid

        instance = _uuid.uuid4().hex[:16]
        with self.lock:
            self.cpp_instances[instance] = target
        ready = self._submit_cpp_call(
            target, {"create_actor": cls, "instance": instance},
            msg.get("args"))
        return {"instance": instance, "ready_obj": ready}

    def _op_submit_cpp_actor_task(self, conn, msg):
        instance = msg["instance"]
        with self.lock:
            target = self.cpp_instances.get(instance)
        if target is None:
            raise ValueError(f"unknown C++ actor instance {instance!r}")
        return self._submit_cpp_call(
            target, {"method": msg["method"], "instance": instance},
            msg.get("args"))

    def _op_submit_named_task(self, conn, msg):
        """Cross-language task submission (cpp/ frontend; counterpart of
        the reference's cross-language FunctionDescriptor calls): invoke
        a Python function registered under a name
        (ray_tpu.register_named_function) with JSON-decoded args —
        or a C++-defined function if a C++ worker registered the name
        (_op_register_cpp_functions).
        Returns the return object's hex for polling via get_object_json."""
        from ray_tpu.core.ids import ObjectID as OID
        from ray_tpu.core.ids import TaskID
        from ray_tpu.core.serialization import serialize
        from ray_tpu.core.task_spec import TaskArg

        name = msg["name"]
        with self.lock:
            cpp_target = self.cpp_functions.get(name)
        if cpp_target is not None:
            return self._submit_cpp_call(
                cpp_target, {"fn": name}, msg.get("args"))
        with self.lock:
            func_id = self.kv.get(f"__named_fn__/{name}")
        if func_id is None:
            raise ValueError(f"no function registered as {name!r}")
        func_id = func_id.decode() if isinstance(func_id, bytes) else func_id
        args = []
        for a in msg.get("args", []):
            if (isinstance(a, dict) and set(a) == {"__ref__"}
                    and isinstance(a["__ref__"], str)
                    and len(a["__ref__"]) == 28
                    and all(c in "0123456789abcdef"
                            for c in a["__ref__"])):
                # Cross-language ObjectRef marker: a real ref arg, so
                # the executing worker pulls the value from the object
                # plane (zero JSON round-trip for plasma values).
                args.append(TaskArg(is_ref=True, object_hex=a["__ref__"]))
            else:
                args.append(TaskArg(is_ref=False,
                                    data=serialize(a).to_bytes()))
        return_id = OID.from_random()
        owner = conn.meta.get("worker_hex", "")
        spec = TaskSpec(
            task_id=TaskID.from_random(), func_id=func_id, func_blob=None,
            args=args, num_returns=1, return_ids=[return_id],
            resources={"CPU": float(msg.get("num_cpus", 1.0)),
                       **({"TPU": float(msg["num_tpus"])}
                          if msg.get("num_tpus") else {})},
            max_retries=int(msg.get("max_retries", 0)),
            name=f"named:{name}", owner=owner)
        self._op_submit_task(conn, {"spec": spec})
        return return_id.hex()

    def _op_get_object_json(self, conn, msg):
        """Poll an object's value for non-Python clients: deserializes
        and re-encodes as JSON. {"status": "pending"|"ready"|"error"}."""
        import json as _json

        with self.lock:
            entry = self.objects.get(msg["obj"])
            if entry is None:
                return {"status": "error", "error": "object not found"}
            if entry.state == PENDING:
                return {"status": "pending"}
        reply = self._op_fetch_object(
            conn, {"obj": msg["obj"], "with_meta": True})
        if reply is None or reply.get("data") is None:
            return {"status": "error",
                    "error": "object payload unavailable"}
        payload, is_error = reply["data"], reply["is_error"]
        from ray_tpu.core.serialization import deserialize

        try:
            value = deserialize(payload)
        except Exception as e:  # noqa: BLE001
            return {"status": "error",
                    "error": f"undeserializable result: {e}"}
        if is_error:
            return {"status": "error", "error": f"{value}"}
        from ray_tpu.core.rpc import _to_jsonable

        try:
            # Validate the WIRE encoding (bytes become base64 envelopes);
            # allow_nan=False because bare NaN/Infinity tokens are not
            # JSON and break non-Python parsers.
            _json.dumps(_to_jsonable(value), allow_nan=False)
        except (TypeError, ValueError):
            return {"status": "error",
                    "error": f"result of type {type(value).__name__} is "
                             "not JSON-representable; fetch it from a "
                             "Python client"}
        return {"status": "ready", "value": value}

    def _op_task_done(self, conn, msg):
        with self.lock:
            self._drain_submit_ingress_locked()
            # Batched result puts ride the done message (worker.py
            # _finish); store them BEFORE completing the task so
            # subscribers resolve before any retry bookkeeping.
            put_node = self._store_node_for(conn)
            for put in msg.get("puts", ()):
                self._store_object_locked(
                    put["obj"], inline=put.get("inline"),
                    size=put["size"],
                    is_error=put.get("is_error", False),
                    in_shm=put.get("in_shm", False),
                    node_id=put_node)
            rec = self.tasks.get(msg["task_id"])
            worker_hex = conn.meta.get("worker_hex")
            w = self.workers.get(worker_hex) if worker_hex else None
            if rec is not None:
                rec.state = "FAILED" if msg.get("failed") else "FINISHED"
                rec.finished_at = time.time()
                tr = msg.get("trace")
                if tr:
                    rec.trace_id, rec.span_id, rec.parent_span_id = tr
            claimed = None
            need_wake = True
            if w is not None and w.kind == "pool":
                w.state = "idle"
                w.current_task = None
                released = w.acquired
                self._release(w)
                # Fast redispatch: hand this worker the next compatible
                # pending task WITHOUT a full scheduler pass (a 1k-task
                # burst used to trigger 1k O(pending) rescans, one per
                # completion).  Conservative: plain tasks only; anything
                # with placement/strategy/PG falls back to the pass.
                claimed = self._fast_claim_locked(w)
                if claimed is not None:
                    # The pass is still needed when this completion could
                    # have unblocked anything BEYOND the claimed task:
                    # leftover freed resources (shapes differ), a put
                    # that made a dep-blocked task ready (which may need
                    # a worker SPAWN, not just an idle worker), an idle
                    # worker for it, or queued actors/PGs.
                    if not self.pending_tasks:
                        self._dep_waiters.clear()
                    unblocked = any(
                        p["obj"] in self._dep_waiters
                        for p in msg.get("puts", ()))
                    need_wake = bool(
                        unblocked
                        or released.to_dict()
                        != ResourceSet(claimed.resources).to_dict()
                        or self.pending_actors
                        or any(pg.state == "PENDING"
                               for pg in self.placement_groups.values())
                        or any(x.kind == "pool" and x.state == "idle"
                               and x.conn is not None
                               for x in self.workers.values()))
            self._prune_lineage_locked()
        for obj_hex in msg.get("decrefs", ()):
            self._op_decref(conn, {"obj": obj_hex})
        if any(p.get("in_shm") for p in msg.get("puts", ())):
            self._maybe_spill()
        if claimed is not None:
            try:
                w.conn.push({"op": "execute_task", "spec": claimed})
            except Exception:
                with self.lock:
                    self._mark_worker_dead(w, "push failed")
                need_wake = True
        if need_wake:
            self._wake.set()

    def _fast_claim_locked(self, w) -> Optional[TaskSpec]:
        """Lock held.  Pop the first plain pending task this idle worker
        can run right now (deps ready, same env, resources fit its
        node); None defers to the scheduling pass."""
        node = self.nodes.get(w.node_id)
        if node is None or not node.alive:
            return None
        pending = self.pending_tasks
        for i in range(min(len(pending), 64)):
            spec = pending[i]
            if (spec.placement_group_hex
                    or spec.scheduling_strategy is not None
                    or not self._deps_ready(spec)):
                continue
            if self._env_key_for(spec.resources, spec.runtime_env) \
                    != w.env_key:
                continue
            need = ResourceSet(spec.resources)
            if not need.is_subset_of(node.available):
                continue
            del pending[i]
            node.available = node.available.subtract(need)
            self._index_touch(w.node_id)
            w.acquired = need
            w.charge = ("node", w.node_id)
            w.state = "busy"
            w.current_task = spec.task_id.hex()
            rec = self.tasks.get(spec.task_id.hex())
            if rec is not None:
                rec.state = "RUNNING"
                rec.worker_hex = w.worker_hex
                rec.started_at = time.time()
                rec.arg_bytes = self._task_arg_bytes(spec)
            return spec
        return None

    # ------------------------------------------------------------------
    # Worker leases: the owner-direct task path's only head involvement
    # (reference: NodeManager::HandleRequestWorkerLease
    # node_manager.cc:1794 grants a worker binding; the owner then
    # pushes tasks peer-to-peer, direct_task_transport.h:75).
    def _op_request_lease(self, conn, msg):
        owner_hex = conn.meta.get("worker_hex", "")
        count = max(1, min(int(msg.get("count", 1)),
                           self.config.max_lease_workers_per_request))
        resources = msg.get("resources") or {}
        renv = msg.get("runtime_env")
        token = msg.get("token")
        granted: List[dict] = []
        denied = 0
        error = ""
        with self.lock:
            env_key = self._env_key_for(resources, renv)
            broken = self.broken_envs.get(env_key)
            if broken is not None and \
                    time.time() - broken[1] <= self.broken_env_ttl_s:
                denied, error = count, f"runtime_env setup failed: " \
                    f"{broken[0]}"
                count = 0
            need = ResourceSet(resources)
            # Virtual availability across the grant loop, so N spawn
            # decisions spread over nodes instead of all landing on the
            # first pick (mirrors the schedule pass's virtual view).
            avail_virtual: Dict[str, ResourceSet] = {}

            def virt(nid: str) -> ResourceSet:
                if nid not in avail_virtual:
                    node = self.nodes.get(nid)
                    av = (node.available if node is not None
                          and node.alive else ResourceSet())
                    # Earlier queued lease demand already spoken for on
                    # this node reduces what THIS request can plan with.
                    # Indexed by node: O(demand on nid), not O(all
                    # pending) — the scan that made lease admission
                    # quadratic under many-owner contention.
                    for pl in self.pending_leases.node_demand(nid):
                        pneed = ResourceSet(pl["resources"])
                        av = av.subtract(pneed) \
                            if pneed.is_subset_of(av) else ResourceSet()
                    avail_virtual[nid] = av
                return avail_virtual[nid]

            node_workers: Dict[str, int] = {}
            starting_total = 0
            for w in self.workers.values():
                if w.kind == "pool" and w.state != "dead":
                    node_workers[w.node_id] = node_workers.get(
                        w.node_id, 0) + 1
                    if w.state == "starting" and w.env_key == env_key:
                        starting_total += 1
            # Spawns already claimed by earlier queued lease requests
            # must not dedupe THIS request's spawns.
            unclaimed = starting_total \
                - self.pending_leases.env_count(env_key)
            # Fair-share clamp under competition: with other owners
            # holding leases or queued demand, one burst's ask must not
            # swallow the whole free pool first-come-take-all — the
            # losers would crawl on a single worker while the winner
            # hoards, and concurrent-submitter throughput is gated by
            # the slowest owner.  Denied remainders retry after backoff
            # and pick up whatever share frees.
            others = {w.leased_to for w in self.workers.values()
                      if w.kind == "pool" and w.state == "leased"
                      and w.leased_to and w.leased_to != owner_hex}
            others.update(self.pending_leases.owners_except(owner_hex))
            if others and count > 1:
                free_fit = sum(virt(n.node_id).fit_count(need)
                               for n in self.nodes.values()
                               if n.schedulable)
                share = max(1, free_fit // (len(others) + 1))
                if count > share:
                    denied += count - share
                    clamped_from, count = count, share
                    if self._m_lease_clamps is not None:
                        try:
                            self._m_lease_clamps.inc()
                        except Exception:
                            pass
                    try:
                        from ray_tpu.util import flight_recorder

                        flight_recorder.record(
                            "scheduler", "fair_share_clamp",
                            owner=owner_hex, asked=clamped_from,
                            share=share, competitors=len(others))
                    except Exception:
                        pass
            for i in range(count):
                w = self._idle_lease_worker_locked(env_key, need, virt)
                if w is not None:
                    charge = ("node", w.node_id)
                    avail_virtual[w.node_id] = virt(
                        w.node_id).subtract(need)
                    self._charge_target_subtract(charge, need)
                    w.acquired = need
                    w.charge = charge
                    w.state = "leased"
                    w.leased_to = owner_hex
                    granted.append({"worker": w.worker_hex,
                                    "address": w.address})
                    continue
                # No idle worker: place a spawn (virtual accounting) or
                # deny the remainder fast — the owner pipelines onto
                # what it has and retries after a backoff.
                feasible = [n for n in self.nodes.values()
                            if n.schedulable and need.is_subset_of(
                                virt(n.node_id))]
                if not feasible:
                    # Workers granted THIS call count as "have": the
                    # owner sent have= before any grant arrived, and an
                    # infeasible remainder queued behind a partial grant
                    # would pin the owner's requested counter (and its
                    # pipeline depth) until capacity frees — which never
                    # happens while the owner itself holds it.
                    if int(msg.get("have", 0)) + len(granted) > 0:
                        # Owner has workers to pipeline onto: deny the
                        # excess fast (it backs off and retries).
                        denied += count - i
                    else:
                        # Nothing to pipeline onto: queue the demand —
                        # it must stay visible to the autoscaler
                        # (get_load) and grants when capacity appears.
                        for _ in range(count - i):
                            self.pending_leases.append({
                                "owner": owner_hex, "env_key": env_key,
                                "resources": dict(resources),
                                "token": token, "node_id": "",
                                "created": time.time()})
                    break
                node = max(feasible, key=lambda n: (
                    self._utilization(n, virt(n.node_id)), n.is_head))
                nid = node.node_id
                avail_virtual[nid] = virt(nid).subtract(need)
                if unclaimed > 0:
                    unclaimed -= 1  # one already on the way
                elif node_workers.get(nid, 0) < \
                        self.config.max_workers_per_node:
                    self._spawn_worker(env_key=env_key, kind="pool",
                                       node_id=nid)
                    node_workers[nid] = node_workers.get(nid, 0) + 1
                self.pending_leases.append({
                    "owner": owner_hex, "env_key": env_key,
                    "resources": dict(resources), "token": token,
                    "node_id": nid, "created": time.time()})
        self._push_lease_grants([(conn, token, granted, denied, error)])

    def _idle_lease_worker_locked(self, env_key: str, need: "ResourceSet",
                                  avail_of=None):
        """Lock held.  Any idle pool worker with the right env whose
        node can hold the lease's resources."""
        for x in self.workers.values():
            if (x.kind == "pool" and x.state == "idle"
                    and x.conn is not None and x.env_key == env_key
                    and x.address):
                node = self.nodes.get(x.node_id)
                if node is None or not node.alive:
                    continue
                avail = avail_of(x.node_id) if avail_of is not None \
                    else node.available
                if need.is_subset_of(avail):
                    return x
        return None

    def _op_release_lease(self, conn, msg):
        owner_hex = conn.meta.get("worker_hex", "")
        with self.lock:
            for whex in msg.get("workers", ()):
                w = self.workers.get(whex)
                if w is not None and w.state == "leased" and \
                        (not owner_hex or w.leased_to == owner_hex):
                    self._release(w)
                    w.state = "idle"
                    w.leased_to = ""
        self._wake.set()

    def _op_kill_worker(self, conn, msg):
        """Owner-initiated kill of a leased worker (force-cancel of a
        lease-path task; reference: CancelTask with force_kill kills
        the executing worker)."""
        whex = msg.get("worker")
        owner_hex = conn.meta.get("worker_hex", "")
        with self.lock:
            w = self.workers.get(whex)
            if w is None or w.state == "dead":
                return False
            if w.state == "leased" and owner_hex and \
                    w.leased_to != owner_hex:
                return False  # only the lease holder may kill
            node = self.nodes.get(w.node_id)
            if w.proc is not None:
                try:
                    w.proc.kill()
                except OSError:
                    pass
            elif node is not None and node.conn is not None:
                try:
                    node.conn.push({"op": "kill_worker",
                                    "worker_hex": whex})
                except Exception:
                    pass
            else:
                return False
            self._mark_worker_dead(w, "killed by owner (task cancelled)")
        self._wake.set()
        return True

    def _try_grant_leases_locked(self) -> List[tuple]:
        """Lock held.  Match queued lease requests against idle workers
        / freed resources; expired ones are denied so the owner's pump
        re-requests.  Returns (conn, token, workers, denied) tuples to
        push outside the lock."""
        if not self.pending_leases:
            return []
        out: List[tuple] = []
        still: List[dict] = []
        now = time.time()
        # Per-pass spawn accounting: queued demand may target nodes
        # that joined AFTER the request (autoscaler growth) — spawn
        # there, deduped against already-starting workers.
        node_workers: Dict[str, int] = {}
        starting: Dict[str, int] = {}
        leased_by: Dict[tuple, int] = {}
        for w in self.workers.values():
            if w.kind == "pool" and w.state != "dead":
                node_workers[w.node_id] = node_workers.get(
                    w.node_id, 0) + 1
                if w.state == "starting":
                    starting[w.env_key] = starting.get(w.env_key, 0) + 1
                if w.state == "leased":
                    key = (w.leased_to, w.env_key)
                    leased_by[key] = leased_by.get(key, 0) + 1
        for pl in self.pending_leases:
            owner = self.workers.get(pl["owner"])
            if owner is None or owner.state == "dead" or owner.conn is None:
                continue  # owner gone: drop the demand
            need = ResourceSet(pl["resources"])
            w = self._idle_lease_worker_locked(pl["env_key"], need)
            if w is None:
                broken = self.broken_envs.get(pl["env_key"])
                if broken is not None and \
                        now - broken[1] <= self.broken_env_ttl_s:
                    # Env poisoned AFTER this request was queued (its
                    # own spawn usually revealed the poison) and no
                    # healthy idle worker can serve it: deny with the
                    # setup error so the owner fast-fails its queued
                    # specs — without this the loop would re-spawn
                    # doomed workers forever while the owner waits.
                    # (With healthy idle workers — an earlier setup of
                    # the same env succeeded — the demand is served,
                    # not failed.)
                    out.append((owner.conn, pl["token"], [], 1,
                                f"runtime_env setup failed: {broken[0]}"))
                    continue
            if w is not None:
                charge = ("node", w.node_id)
                self._charge_target_subtract(charge, need)
                w.acquired = need
                w.charge = charge
                w.state = "leased"
                w.leased_to = pl["owner"]
                out.append((owner.conn, pl["token"],
                            [{"worker": w.worker_hex,
                              "address": w.address}], 0, ""))
                continue
            if starting.get(pl["env_key"], 0) > 0:
                starting[pl["env_key"]] -= 1  # a spawn is on the way
                still.append(pl)
                continue
            feasible = [n for n in self.nodes.values()
                        if n.schedulable and need.is_subset_of(n.available)
                        and node_workers.get(n.node_id, 0)
                        < self.config.max_workers_per_node]
            if feasible:
                node = max(feasible, key=lambda n: (
                    self._utilization(n), n.is_head))
                self._spawn_worker(env_key=pl["env_key"], kind="pool",
                                   node_id=node.node_id)
                node_workers[node.node_id] = node_workers.get(
                    node.node_id, 0) + 1
                still.append(pl)
            elif leased_by.get((pl["owner"], pl["env_key"]), 0) > 0:
                # Cluster-infeasible remainder of a request whose owner
                # now holds same-shaped workers: deny now, exactly as
                # _op_request_lease does for have>0 askers.  Keeping it
                # queued would pin the owner's requested counter — and
                # with it the owner's pipeline depth — on capacity the
                # owner itself occupies.
                out.append((owner.conn, pl["token"], [], 1, ""))
            elif now - pl["created"] > (10.0 if pl.get("node_id")
                                        else 15.0):
                # Spawn never materialized (10s), or cluster-infeasible
                # demand went stale (15s): deny so the owner's pump
                # re-requests — a still-wanting owner refreshes the
                # entry within its backoff, keeping the demand visible
                # to the autoscaler without leaking dead entries.
                out.append((owner.conn, pl["token"], [], 1, ""))
            else:
                still.append(pl)
        self.pending_leases.reset(still)
        return out

    def _push_lease_grants(self, grants: List[tuple]):
        for oconn, token, workers, denied, error in grants:
            if not workers and not denied:
                continue
            # Single choke point for both grant paths (request-time and
            # scheduler-loop): count the decision and drop it in the
            # flight-recorder ring for the timeline's scheduler lane.
            try:
                if workers and self._m_lease_grants is not None:
                    self._m_lease_grants.inc(len(workers))
                if denied and self._m_lease_denials is not None:
                    self._m_lease_denials.inc(denied)
            except Exception:
                pass
            try:
                from ray_tpu.util import flight_recorder

                flight_recorder.record(
                    "scheduler", "lease_grant",
                    granted=len(workers), denied=denied,
                    workers=[wi["worker"][:8] for wi in workers],
                    error=error or "")
            except Exception:
                pass
            try:
                oconn.push({"op": "lease_granted", "token": token,
                            "workers": workers, "denied": denied,
                            "error": error})
            except Exception:
                # Owner unreachable: reclaim the workers.
                with self.lock:
                    for wi in workers:
                        x = self.workers.get(wi["worker"])
                        if x is not None and x.state == "leased":
                            self._release(x)
                            x.state = "idle"
                            x.leased_to = ""

    def _op_task_events(self, conn, msg):
        """Batched execution events from workers running lease-path
        tasks (reference TaskEventBuffer → GcsTaskManager,
        task_event_buffer.h:206): keeps the state API and timeline
        complete for tasks the head never scheduled."""
        now = time.time()
        worker_hex = conn.meta.get("worker_hex", "")
        events = msg.get("events", ())
        try:
            if self._m_task_event_frames is not None:
                self._m_task_event_frames.inc()
                self._m_task_events.inc(len(events))
        except Exception:
            pass
        # GLOBAL-LOCK-FREE completion drain: task records live in the
        # sharded table (insert/pop are shard-locked internally), each
        # task's events come from its single executing worker, and the
        # merged fields are telemetry the scheduler never branches on
        # for head-path liveness (the direct/PENDING-RUNNING guard
        # below keeps retry state authoritative).  The highest-volume
        # op on a loaded head no longer serializes behind the
        # scheduler's lock.
        w = self.workers.get(worker_hex)
        for ev in events:
            rec = self.tasks.get(ev["task_id"])
            if rec is None:
                spec = TaskSpec(
                    task_id=TaskID.from_hex(ev["task_id"]),
                    func_id="", func_blob=None, args=[],
                    num_returns=1, return_ids=[], resources={},
                    max_retries=int(ev.get("retries_left", 0)),
                    name=ev.get("name", ""),
                    owner=ev.get("owner", ""), direct=True)
                rec = self.tasks[ev["task_id"]] = TaskRecord(
                    spec=spec, submitted_at=ev.get("start")
                    or ev.get("received") or now)
            elif not rec.spec.direct and rec.state in ("PENDING",
                                                       "RUNNING"):
                # A live head-path record (the task was fallback-
                # resubmitted through the scheduler after its lease
                # worker was presumed lost): a stale event from the
                # old worker must not clobber the retry's state or
                # its death-detection worker binding.
                continue
            state = ev.get("state", "FINISHED")
            # Arrival-only deltas map into the head's state
            # vocabulary (PENDING|RUNNING|FINISHED|FAILED).
            rec.state = "PENDING" if state == "RECEIVED" else state
            rec.worker_hex = worker_hex
            # Deltas carry only what changed since the last event for
            # this task (an arrival-only RECEIVED has no start/end):
            # merge, never clobber with zeros.
            rec.started_at = ev.get("start", 0.0) or rec.started_at
            rec.finished_at = ev.get("end", 0.0) or rec.finished_at
            rec.received_at = ev.get("received", 0.0) or rec.received_at
            rec.retry_count = ev.get("retry_count", rec.retry_count)
            tr = ev.get("trace")
            if tr:
                rec.trace_id, rec.span_id, rec.parent_span_id = tr
            # Track the leased worker's current task so the OOM
            # victim policy can pick/kill it like a busy worker.
            if w is not None and w.state == "leased":
                if state == "RUNNING":
                    w.current_task = ev["task_id"]
                elif w.current_task == ev["task_id"]:
                    w.current_task = None
        cap = self.config.max_lineage_entries
        if cap > 0 and len(self.tasks) > cap:
            with self.lock:
                self._prune_lineage_locked()

    def _op_flight_recorder(self, conn, msg):
        """Dump the head's in-memory flight-recorder ring (recent wire
        flushes + scheduler decisions) — the dashboard merges this with
        the driver-side ring when the head is a separate process."""
        from ray_tpu.util import flight_recorder

        return {"events": flight_recorder.dump(
                    int(msg.get("last", 0) or 0),
                    float(msg.get("since", 0) or 0.0)),
                "stats": flight_recorder.stats()}

    # ------------------------------------------------------------------
    # Actors
    def _op_create_actor(self, conn, msg):
        spec: ActorCreationSpec = msg["spec"]
        with self.lock:
            entry = ActorEntry(spec=spec)
            self.actors[spec.actor_id.hex()] = entry
            if spec.name:
                key = (spec.namespace, spec.name)
                if key in self.named_actors:
                    entry.state = A_DEAD
                    entry.death_reason = f"name {spec.name!r} already taken"
                    self._push_actor_update(entry, spec.actor_id.hex())
                    return
                self.named_actors[key] = spec.actor_id.hex()
            self.pending_actors.append(spec)
            self._journal_put(f"actor/{spec.actor_id.hex()}", spec)
        self._wake.set()

    def _op_actor_ready(self, conn, msg):
        actor_hex = msg["actor"]
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                return
            if entry.state == A_DEAD:
                # Killed while the worker was still creating the instance —
                # don't resurrect; tell the worker to exit (zombie would
                # otherwise hold its resource allocation).
                try:
                    conn.push({"op": "exit"})
                except Exception:
                    pass
                return
            announcer = conn.meta.get("worker_hex")
            if entry.state == A_ALIVE and entry.worker_hex \
                    and entry.worker_hex != announcer:
                cur = self.workers.get(entry.worker_hex)
                if cur is not None and cur.state != "dead" \
                        and cur.conn is not None:
                    # Fencing: the actor was respawned (e.g. restart
                    # grace expired) and its ORIGINAL worker re-announced
                    # late — one instance must win, the late announcer
                    # exits (reference: GCS actor-registration fencing).
                    try:
                        conn.push({"op": "exit"})
                    except Exception:
                        pass
                    return
            entry.state = A_ALIVE
            entry.address = msg["address"]
            # Bind the announcing worker: after a head restart the actor
            # re-announces from a worker this head never spawned, and the
            # binding is what routes death-detection → actor restart.
            worker_hex = conn.meta.get("worker_hex")
            if worker_hex:
                entry.worker_hex = worker_hex
                w = self.workers.get(worker_hex)
                if w is not None:
                    w.actor_hex = actor_hex
                    w.kind = "actor"
            self._restored_actors.discard(actor_hex)
            self._push_actor_update(entry, actor_hex)

    def _op_actor_creation_failed(self, conn, msg):
        actor_hex = msg["actor"]
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                return
            entry.state = A_DEAD
            entry.death_reason = msg.get("reason", "creation failed")
            self._push_actor_update(entry, actor_hex)

    def _op_subscribe_actor(self, conn, msg):
        actor_hex = msg["actor"]
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                conn.push({"op": "actor_update", "actor": actor_hex,
                           "state": A_DEAD, "address": "",
                           "reason": "no such actor"})
                return
            conn.push(self._actor_update_msg(entry, actor_hex))
            if entry.state not in (A_DEAD,):
                entry.subscribers.append(conn)

    def _op_kill_actor(self, conn, msg):
        actor_hex = msg["actor"]
        no_restart = msg.get("no_restart", True)
        with self.lock:
            entry = self.actors.get(actor_hex)
            if entry is None:
                return
            if no_restart:
                entry.spec.max_restarts = entry.spec.restart_count
            w = self.workers.get(entry.worker_hex)
            if w is not None and w.conn is not None:
                try:
                    w.conn.push({"op": "exit"})
                except Exception:
                    pass
            if entry.state == A_PENDING or (w is None and entry.state != A_DEAD):
                entry.state = A_DEAD
                entry.death_reason = "killed"
                self.pending_actors = [
                    s for s in self.pending_actors
                    if s.actor_id.hex() != actor_hex
                ]
                self._fail_actor_inflight(actor_hex, "killed")
                self._push_actor_update(entry, actor_hex)

    def _actor_update_msg(self, entry: ActorEntry, actor_hex: str):
        return {
            "op": "actor_update",
            "actor": actor_hex,
            "state": entry.state,
            "address": entry.address,
            "reason": entry.death_reason,
            # Owners use this to resubmit delivered-but-unfinished
            # direct calls across a restart (runtime max_task_retries;
            # getattr: journal-replayed specs may predate the field).
            "max_task_retries": getattr(entry.spec, "max_task_retries",
                                        0),
        }

    def _push_actor_update(self, entry: ActorEntry, actor_hex: str):
        msg = self._actor_update_msg(entry, actor_hex)
        subs = list(entry.subscribers)
        if entry.state == A_DEAD:
            entry.subscribers = []
            self._journal_del(f"actor/{actor_hex}")
            # Release the actor's name so it can be reused (the reference
            # unregisters names on death, gcs_actor_manager.cc).  Guard on
            # ownership: an actor that died *because* the name was taken
            # must not free the live owner's registration.
            if entry.spec.name:
                key = (entry.spec.namespace, entry.spec.name)
                if self.named_actors.get(key) == actor_hex:
                    del self.named_actors[key]
        for c in subs:
            try:
                c.push(msg)
            except Exception:
                pass

    def _op_get_named_actor(self, conn, msg):
        key = (msg.get("namespace", ""), msg["name"])
        with self.lock:
            actor_hex = self.named_actors.get(key)
            if actor_hex is None:
                return None
            entry = self.actors.get(actor_hex)
            if entry is None or entry.state == A_DEAD:
                return None
            return {"actor": actor_hex, "class_id": entry.spec.class_id,
                    "state": entry.state, "address": entry.address}

    def _op_list_named_actors(self, conn, msg):
        with self.lock:
            out = []
            for (ns, name), actor_hex in self.named_actors.items():
                entry = self.actors.get(actor_hex)
                if entry is not None and entry.state != A_DEAD:
                    out.append({"name": name, "namespace": ns})
            return out

    # ------------------------------------------------------------------
    # State API (reference: util/state — ray list tasks/actors/...)
    def _op_cluster_resources(self, conn, msg):
        with self.lock:
            out = ResourceSet()
            for n in self.nodes.values():
                if n.alive:
                    out = out.add(n.total)
            return out.to_dict()

    def _op_available_resources(self, conn, msg):
        with self.lock:
            out = ResourceSet()
            for n in self.nodes.values():
                if n.alive:
                    out = out.add(n.available)
            # PG free reservations still count as available-to-PG-users
            return out.to_dict()

    def _op_list_tasks(self, conn, msg):
        with self.lock:
            self._drain_submit_ingress_locked()
            return [
                {"task_id": h, "name": r.spec.name, "state": r.state,
                 "worker": r.worker_hex,
                 "submitted_at": r.submitted_at or None,
                 "started_at": r.started_at or None,
                 "finished_at": r.finished_at or None,
                 "received_at": r.received_at or None,
                 "retry_count": r.retry_count,
                 "trace_id": r.trace_id or None,
                 "span_id": r.span_id or None,
                 "parent_span_id": r.parent_span_id or None,
                 "pid": (self.workers.get(r.worker_hex).pid
                         if r.worker_hex in self.workers else None),
                 "duration_s": (r.finished_at - r.started_at)
                 if r.finished_at else None}
                for h, r in self.tasks.items()
            ]

    def _op_list_actors(self, conn, msg):
        with self.lock:
            return [
                {"actor_id": h, "state": e.state, "name": e.spec.name,
                 "class": e.spec.class_id.split(":")[0],
                 "pid": (self.workers.get(e.worker_hex).pid
                         if e.worker_hex in self.workers else None)}
                for h, e in self.actors.items()
            ]

    def _op_list_objects(self, conn, msg):
        with self.lock:
            return [
                {"object_id": h, "state": e.state, "size": e.size,
                 "refcount": e.refcount, "in_shm": e.in_shm,
                 "spilled": e.spilled_uri is not None}
                for h, e in self.objects.items()
            ]

    def _op_list_workers(self, conn, msg):
        with self.lock:
            return [
                {"worker_id": h, "kind": w.kind, "state": w.state,
                 "pid": w.pid, "actor": w.actor_hex}
                for h, w in self.workers.items()
            ]

    def _op_ping(self, conn, msg):
        return "pong"

    # ------------------------------------------------------------------
    # Nodes (fake-cluster API, counterpart of cluster_utils.Cluster
    # add_node/remove_node, python/ray/cluster_utils.py:201/:279)
    def _op_add_node(self, conn, msg):
        res = ResourceSet(msg["resources"])
        node_id = msg.get("node_id")
        with self.lock:
            if not node_id:
                i = len(self.nodes)
                while f"node-{i}" in self.nodes:
                    i += 1
                node_id = f"node-{i}"
            if node_id in self.nodes:
                raise ValueError(f"node {node_id} already exists")
            self.nodes[node_id] = NodeState(
                node_id=node_id, total=res, available=res,
                labels=msg.get("labels") or {})
            self._index_touch(node_id)
            self._journal_put(f"node/{node_id}", {
                "resources": res.to_dict(),
                "labels": msg.get("labels") or {}})
        self._wake.set()
        return node_id

    # -- graceful node drain (reference DrainRaylet,
    # src/ray/protobuf/node_manager.proto:401, and autoscaler DrainNode,
    # autoscaler.proto:334) ---------------------------------------------
    def _op_drain_node(self, conn, msg):
        """Begin draining a node: it stops accepting leases/placements
        NOW; the drain sweep migrates sole-copy objects, reschedules
        idle PG bundles, waits for running work, then terminates it."""
        node_id = msg["node_id"]
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return {"accepted": False, "reason": "no such alive node"}
            if node.is_head:
                return {"accepted": False, "reason": "cannot drain head"}
            node.draining = True
            node.drain_reason = msg.get("reason", "")
            self._index_touch(node_id)
            self._drain_migrating.setdefault(node_id, set())
            # Journaled: a restarted head must keep draining (the
            # autoscalers are waiting on drain_status == "gone"; losing
            # the flag would wedge them in DRAINING forever).
            self._journal_put(f"drain/{node_id}",
                              {"reason": node.drain_reason})
        self._wake.set()
        return {"accepted": True}

    def _op_drain_status(self, conn, msg):
        node_id = msg["node_id"]
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return {"state": "gone"}
            if not node.draining:
                return {"state": "alive"}
            busy = sum(1 for w in self.workers.values()
                       if self._drain_blocking_locked(w, node_id))
            sole = sum(1 for e in self.objects.values()
                       if e.node_id == node_id and e.in_shm
                       and e.state == READY)
            bundles = sum(1 for pg in self.placement_groups.values()
                          if pg.state == "CREATED" and any(
                              b.node_id == node_id for b in pg.bundles))
            return {"state": "draining", "busy_workers": busy,
                    "sole_objects": sole, "pg_bundles": bundles}

    def _op_objects_migrated(self, conn, msg):
        """A draining node finished pushing objects to a survivor: move
        the primary-copy records so the upcoming node death triggers NO
        reconstruction for them."""
        node_id = msg["node_id"]
        dest_node = msg["dest_node"]
        with self.lock:
            migr = self._drain_migrating.get(node_id, set())
            dest = self.nodes.get(dest_node)
            for obj_hex, status in (msg.get("results") or {}).items():
                migr.discard(obj_hex)
                if status in ("ok", "have") and dest is not None \
                        and dest.alive:
                    e = self.objects.get(obj_hex)
                    if e is not None and e.node_id == node_id:
                        e.node_id = dest_node
        self._wake.set()
        return True

    def _drop_drain_state_locked(self, node_id: str):
        """Lock held.  A node leaving the cluster by ANY path (graceful
        finish, crash, removal) must shed its drain bookkeeping and
        journal record, or a head restart re-restores a phantom
        drain."""
        self._drain_migrating.pop(node_id, None)
        self._drain_issued_at.pop(node_id, None)
        self._journal_del(f"drain/{node_id}")

    @staticmethod
    def _drain_blocking_locked(w, node_id: str) -> bool:
        """Lock held.  Does this worker hold drain-blocking work on
        node_id?  (Single definition shared by drain_status and the
        drain sweep so the two can never disagree.)"""
        return (w.node_id == node_id and w.state != "dead"
                and bool(w.current_task or w.actor_hex
                         or w.state in ("leased", "busy", "starting")))

    def _reschedule_pg_locked(self, pg: "PlacementGroupEntry"):
        """Lock held.  Release a CREATED-but-idle PG's bundles and send
        it back to PENDING: the scheduler re-reserves it on schedulable
        nodes (the drain path's bundle migration; reference reschedules
        bundles off draining/dead nodes the same way)."""
        for b in pg.bundles:
            node = self.nodes.get(b.node_id)
            if node is not None and node.alive:
                node.available = node.available.add(b.available)
                self._index_touch(b.node_id)
        pg.bundles = []
        pg.state = "PENDING"

    def _check_drains(self):
        """Drain sweep (called from the scheduler loop): advance every
        draining node toward termination.  Order per node: wait for
        running work -> migrate sole-copy objects to a survivor arena ->
        reschedule idle PG bundles -> terminate via the normal removal
        path (object records already point at the survivor, so the
        death handler reconstructs nothing)."""
        migrations = []  # (node_conn, objects, dest_addr, dest_node)
        finished = []    # node_ids ready to terminate
        with self.lock:
            draining = [n for n in self.nodes.values()
                        if n.alive and n.draining]
            for node in draining:
                nid = node.node_id
                busy = any(self._drain_blocking_locked(w, nid)
                           for w in self.workers.values())
                if busy:
                    continue
                migr = self._drain_migrating.setdefault(nid, set())
                issued = self._drain_issued_at.get(nid, 0.0)
                if migr and time.monotonic() - issued > self._drain_retry_s:
                    # The report for this batch is presumed lost (or the
                    # node restarted mid-migration): re-issue.
                    migr.clear()
                sole = [(h, e) for h, e in self.objects.items()
                        if e.node_id == nid and e.in_shm
                        and e.state == READY]
                pending = [x for x in sole if x[0] in migr]
                fresh = [x for x in sole if x[0] not in migr]
                if fresh and node.conn is not None:
                    dest = next(
                        (n for n in self.nodes.values()
                         if n.schedulable and n.node_id != nid
                         and n.conn is not None and n.address),
                        None)
                    if dest is not None:
                        migr.update(h for h, _ in fresh)
                        self._drain_issued_at[nid] = time.monotonic()
                        migrations.append((
                            nid, node.conn,
                            [{"obj": h, "size": e.size}
                             for h, e in fresh],
                            dest.address, dest.node_id))
                        continue
                    # No survivor arena exists: nothing to migrate to —
                    # fall through and let lineage cover the loss.
                elif pending:
                    continue  # migration in flight; wait for the report
                pgs = [pg for pg in self.placement_groups.values()
                       if pg.state == "CREATED" and any(
                           b.node_id == nid for b in pg.bundles)]
                moved = False
                for pg in pgs:
                    in_use = any(
                        w.charge and w.charge[0] == "pg"
                        and w.charge[1] == pg.pg_hex
                        and w.state != "dead"
                        for w in self.workers.values())
                    if not in_use:
                        self._reschedule_pg_locked(pg)
                        moved = True
                if pgs and not moved:
                    continue  # occupied bundles: wait for their workers
                if moved:
                    continue  # let the scheduler re-reserve first
                finished.append(nid)
        for nid, conn, objects, dest_addr, dest_node in migrations:
            try:
                conn.push({"op": "migrate_objects", "objects": objects,
                           "dest": dest_addr, "dest_node": dest_node})
            except Exception:
                # Failed to even hand the node the migration list: take
                # the hexes back out of the in-flight set so the next
                # sweep retries instead of waiting forever on a report
                # that can never come.
                with self.lock:
                    migr = self._drain_migrating.get(nid)
                    if migr is not None:
                        for item in objects:
                            migr.discard(item["obj"])
        for nid in finished:
            with self.lock:
                self._drop_drain_state_locked(nid)
            self._op_remove_node(None, {"node_id": nid})

    def _op_remove_node(self, conn, msg):
        """Simulated node failure: kill its workers, fail/retry their work.

        The worker-death path handles task retry / actor restart exactly as
        a real crash would (chaos-testing hook, reference RayletKiller
        python/ray/_private/test_utils.py:1536)."""
        node_id = msg["node_id"]
        to_kill = []
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None:
                return False
            conn = node.conn
        if conn is not None:
            # Real node: ask its manager to exit and run the full
            # node-death path NOW (worker fail/retry, PG teardown, object
            # recovery) — the later disconnect then no-ops on the
            # already-dead node.
            try:
                conn.push({"op": "exit"})
            except Exception:
                pass
            self._handle_node_death(node_id)
            return True
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return False
            node.alive = False
            node.available = ResourceSet()
            self._index_touch(node_id)
            self._drop_drain_state_locked(node_id)
            self._journal_del(f"node/{node_id}")
            for w in list(self.workers.values()):
                if w.node_id == node_id and w.state != "dead":
                    to_kill.append(w)
                    if w.conn is None:
                        # Never registered: no disconnect event will ever
                        # fire, so observe the death here or its task/actor
                        # hangs forever.
                        self._mark_worker_dead(w, f"node {node_id} removed")
            # PGs with bundles on this node lose them
            for pg in self.placement_groups.values():
                if pg.state == "CREATED" and any(
                        b.node_id == node_id for b in pg.bundles):
                    self._teardown_pg(pg, reason=f"node {node_id} removed")
        for w in to_kill:
            if w.proc is not None:
                try:
                    w.proc.kill()
                except OSError:
                    pass
            # death is then observed via disconnect -> _mark_worker_dead
        self._wake.set()
        return True

    def _op_shutdown_cluster(self, conn, msg):
        """Remote shutdown (CLI `ray-tpu stop`). Stops off-thread so the
        reply can flush first."""
        threading.Thread(target=self.stop, daemon=True,
                         name="cluster-shutdown").start()
        return True

    def _op_get_load(self, conn, msg):
        """Cluster load snapshot for the autoscaler (counterpart of the
        GCS AutoscalerStateService GetClusterResourceState,
        autoscaler.proto:315 / gcs_autoscaler_state_manager.cc)."""
        with self.lock:
            self._drain_submit_ingress_locked()
            demands = [dict(s.resources) for s in self.pending_tasks]
            demands += [dict(s.resources) for s in self.pending_actors]
            # Unsatisfied worker-lease requests are task demand too
            # (owner-direct tasks never appear in pending_tasks).
            demands += [dict(pl["resources"])
                        for pl in self.pending_leases]
            pg_demands = [
                {"strategy": pg.strategy, "bundles": list(pg.bundle_specs)}
                for pg in self.placement_groups.values()
                if pg.state == "PENDING"
            ]
            nodes = [
                {"node_id": n.node_id, "is_head": n.is_head,
                 "alive": n.alive, "draining": n.draining,
                 "total": n.total.to_dict(),
                 "available": n.available.to_dict(),
                 "labels": dict(n.labels)}
                for n in self.nodes.values()
            ]
        return {"demands": demands, "pg_demands": pg_demands,
                "nodes": nodes}

    def _op_list_nodes(self, conn, msg):
        self._sample_head_stats()
        with self.lock:
            return [
                {"node_id": n.node_id, "alive": n.alive,
                 "draining": n.draining,
                 "is_head": n.is_head, "resources": n.total.to_dict(),
                 "available": n.available.to_dict(), "labels": n.labels,
                 "address": n.address, "stats": dict(n.stats)}
                for n in self.nodes.values()
            ]

    def _sample_head_stats(self):
        """The head has no reporter thread; sample its host stats on
        read (list_nodes is the only consumer) with the same helper the
        node reporters use."""
        sampler = getattr(self, "_head_stats_sampler", None)
        if sampler is None:
            from ray_tpu.dashboard.reporter import HostStatsSampler

            sampler = self._head_stats_sampler = HostStatsSampler()
        try:
            with self.lock:
                # HEAD-LOCAL workers only: self.workers is the
                # cluster-wide registry (remote workers register with
                # their node_id), and the per-node gauge must not
                # attribute them to the head.
                nw = sum(1 for w in self.workers.values()
                         if w.state != "dead"
                         and w.node_id in ("", "head"))
            stats = sampler.sample(store=self.store, num_workers=nw)
            with self.lock:
                head = self.nodes.get("head")
                if head is not None:
                    head.stats = stats
        except Exception:
            pass

    # ------------------------------------------------------------------
    # Placement groups (counterpart of GcsPlacementGroupManager +
    # 2PC bundle reservation, gcs_placement_group_manager.h:230; bundle
    # policies scheduling/policy/bundle_scheduling_policy.h)
    def _try_reserve_pg(self, pg: PlacementGroupEntry) -> bool:
        """Lock held. Attempt to reserve all bundles atomically (the 2PC
        prepare/commit collapses to one step inside the control plane).
        SPREAD/STRICT_SPREAD walk the utilization-bucketed node index —
        O(bundles) amortized instead of O(nodes x bundles), the scan
        that collapsed create-ready throughput 3.6x at 1,000 PGs on the
        2,000-node sim.  Virtual availability is seeded lazily so a
        small PG on a huge cluster never materializes the full node
        table."""
        needs = [ResourceSet(b) for b in pg.bundle_specs]
        placement: List[str] = []
        # virtual availability during placement, seeded on first touch
        virt: Dict[str, ResourceSet] = {}

        def avail(node_id):
            v = virt.get(node_id)
            if v is None:
                v = virt[node_id] = self.nodes[node_id].available
            return v

        def fits(node_id, need):
            return need.is_subset_of(avail(node_id))

        strategy = pg.strategy
        idx = self._node_index
        if strategy in ("PACK", "STRICT_PACK"):
            alive = [n for n in self.nodes.values() if n.schedulable]
            # try to put everything on one node (best = most utilized that
            # fits all); PACK falls back to spreading the remainder.
            total = ResourceSet(_sum_bundles(pg.bundle_specs))
            for n in sorted(alive, key=self._utilization, reverse=True):
                if total.is_subset_of(n.available):
                    placement = [n.node_id] * len(needs)
                    break
            if not placement:
                if strategy == "STRICT_PACK":
                    return False
                placement = []
                for need in needs:
                    cand = next((n.node_id for n in sorted(
                        alive, key=self._utilization, reverse=True)
                        if fits(n.node_id, need)), None)
                    if cand is None:
                        return False
                    placement.append(cand)
                    virt[cand] = avail(cand).subtract(need)
        elif strategy in ("SPREAD", "STRICT_SPREAD"):
            used_nodes: Set[str] = set()
            placement = []
            if idx is not None:
                for bi, need in enumerate(needs):
                    def fresh_ok(nid, _n=need):
                        return (nid not in used_nodes
                                and _n.is_subset_of(avail(nid)))

                    pick = None
                    for bucket in idx.buckets_low_to_high():
                        pick = idx.probe(bucket, bi, fresh_ok)
                        if pick is not None:
                            break
                    if pick is None and strategy == "SPREAD":
                        # SPREAD tolerates reuse once fresh nodes run out
                        for bucket in idx.buckets_low_to_high():
                            pick = idx.probe(
                                bucket, bi,
                                lambda nid, _n=need:
                                _n.is_subset_of(avail(nid)))
                            if pick is not None:
                                break
                    if pick is None:
                        return False
                    placement.append(pick)
                    used_nodes.add(pick)
                    virt[pick] = avail(pick).subtract(need)
            else:
                alive = [n for n in self.nodes.values() if n.schedulable]
                for need in needs:
                    cands = [n for n in alive if fits(n.node_id, need)]
                    fresh = [n for n in cands
                             if n.node_id not in used_nodes]
                    pool = fresh if fresh else (
                        [] if strategy == "STRICT_SPREAD" else cands)
                    if not pool:
                        return False
                    node = min(pool, key=self._utilization)
                    placement.append(node.node_id)
                    used_nodes.add(node.node_id)
                    virt[node.node_id] = avail(node.node_id).subtract(need)
        else:
            raise ValueError(f"unknown PG strategy {strategy}")

        # commit
        pg.bundles = []
        for i, (need, node_id) in enumerate(zip(needs, placement)):
            node = self.nodes[node_id]
            node.available = node.available.subtract(need)
            self._index_touch(node_id)
            pg.bundles.append(Bundle(index=i, node_id=node_id,
                                     reserved=need, available=need))
        pg.state = "CREATED"
        if pg.ready_obj:
            self._store_object_locked(
                pg.ready_obj,
                inline=_serialized_true(), size=len(_serialized_true()),
                is_error=False)
        return True

    def _teardown_pg(self, pg: PlacementGroupEntry, reason: str):
        """Lock held. Return free bundle reservations; in-use portions come
        back via worker release. Kill actors placed in the PG."""
        for b in pg.bundles:
            node = self.nodes.get(b.node_id)
            if node is not None and node.alive:
                node.available = node.available.add(b.available)
                self._index_touch(b.node_id)
        pg.state = "REMOVED"
        pg.bundles = []
        self._journal_del(f"pg/{pg.pg_hex}")
        # exit workers charged against this PG
        for w in list(self.workers.values()):
            if w.charge and w.charge[0] == "pg" and w.charge[1] == pg.pg_hex:
                if w.conn is not None:
                    try:
                        w.conn.push({"op": "exit"})
                    except Exception:
                        pass
                elif w.state == "starting":
                    # Spawned but not yet registered: it can never receive
                    # the exit push, so mark dead now (releases the charge;
                    # an actor restart attempt then fails via
                    # _unschedulable_reason) and reap the process.  Should
                    # it still register, _op_worker_online sees state=dead
                    # and tells it to exit.
                    self._mark_worker_dead(w, reason)
                    if w.proc is not None:
                        try:
                            w.proc.terminate()
                        except Exception:
                            pass

    def _op_create_pg(self, conn, msg):
        pg = PlacementGroupEntry(
            pg_hex=msg["pg"], strategy=msg.get("strategy", "PACK"),
            bundle_specs=msg["bundles"], ready_obj=msg.get("ready_obj", ""),
            name=msg.get("name", ""))
        with self.lock:
            self.placement_groups[pg.pg_hex] = pg
            if pg.ready_obj:
                self.objects.setdefault(pg.ready_obj, ObjectEntry())
            self._try_reserve_pg(pg)
            self._journal_put(f"pg/{pg.pg_hex}", {
                "strategy": pg.strategy,
                "bundle_specs": pg.bundle_specs,
                "name": pg.name,
                "ready_obj": pg.ready_obj})
        self._wake.set()

    def _op_remove_pg(self, conn, msg):
        with self.lock:
            pg = self.placement_groups.get(msg["pg"])
            if pg is None:
                return False
            if pg.state == "CREATED":
                self._teardown_pg(pg, "removed")
            else:
                pg.state = "REMOVED"
                self._journal_del(f"pg/{pg.pg_hex}")
        self._wake.set()
        return True

    def _op_pg_state(self, conn, msg):
        with self.lock:
            pg = self.placement_groups.get(msg["pg"])
            if pg is None:
                return None
            return {
                "state": pg.state, "strategy": pg.strategy,
                "bundles": [
                    {"index": b.index, "node_id": b.node_id,
                     "reserved": b.reserved.to_dict(),
                     "available": b.available.to_dict()}
                    for b in pg.bundles],
            }

    def _op_list_placement_groups(self, conn, msg):
        with self.lock:
            return [
                {"pg_id": h, "state": pg.state, "strategy": pg.strategy,
                 "name": pg.name, "bundles": pg.bundle_specs}
                for h, pg in self.placement_groups.items()
            ]

    def _op_cancel_object(self, conn, msg):
        """Cancel the task producing this object (ray.cancel(ref))."""
        with self.lock:
            entry = self._object_entry_or_drain_locked(msg["obj"])
            task_hex = entry.producing_task if entry is not None else None
        if not task_hex:
            return False
        return self._op_cancel_task(conn, {"task_id": task_hex,
                                           "force": msg.get("force", False)})

    # ------------------------------------------------------------------
    # Task cancel (counterpart of CoreWorker::CancelTask semantics)
    def _op_cancel_task(self, conn, msg):
        task_hex = msg["task_id"]
        force = msg.get("force", False)
        with self.lock:
            if self._ingress_pending():
                self._drain_submit_ingress_locked()
            rec = self.tasks.get(task_hex)
            if rec is None:
                return False
            if rec.state == "PENDING":
                self.pending_tasks = [
                    s for s in self.pending_tasks
                    if s.task_id.hex() != task_hex]
                rec.state = "CANCELLED"
                self._fail_task_returns_with(
                    rec.spec, "task cancelled", kind="cancelled")
                return True
            if rec.state == "RUNNING" and force:
                w = self.workers.get(rec.worker_hex)
                node = self.nodes.get(w.node_id) if w is not None else None
                killable = w is not None and (
                    w.proc is not None
                    or (node is not None and node.conn is not None))
                if killable:
                    rec.spec.max_retries = rec.spec.retry_count  # no retry
                    rec.state = "CANCELLED"
                    self._fail_task_returns_with(
                        rec.spec, "task cancelled (force)", kind="cancelled")
                    # Kill + mark dead under the lock: releasing first would
                    # let the worker finish, grab another task, and eat the
                    # SIGKILL meant for this one.  kill() is non-blocking.
                    if w.proc is not None:
                        try:
                            w.proc.kill()
                        except OSError:
                            pass
                    else:
                        # Remote worker: its node manager owns the Popen.
                        try:
                            node.conn.push({"op": "kill_worker",
                                            "worker_hex": w.worker_hex})
                        except Exception:
                            pass
                    self._mark_worker_dead(w, "task cancelled")
                    return True
            return False  # running w/o force, or already finished

    def _fail_task_returns_with(self, spec: TaskSpec, reason: str,
                                kind: str = "crashed"):
        """Lock held. kind: crashed | cancelled | unschedulable."""
        from ray_tpu.core.exceptions import (
            TaskCancelledError,
            TaskUnschedulableError,
            WorkerCrashedError,
        )
        from ray_tpu.core.serialization import serialize

        cls = {"cancelled": TaskCancelledError,
               "unschedulable": TaskUnschedulableError}.get(
                   kind, WorkerCrashedError)
        data = serialize(cls(
            f"task {spec.name or spec.task_id.hex()}: {reason}")).to_bytes()
        for oid in spec.return_ids:
            entry = self.objects.get(oid.hex())
            if entry is None or entry.state == PENDING:
                self._store_object_locked(
                    oid.hex(), inline=data, size=len(data), is_error=True)
        if getattr(spec, "is_streaming", False):
            # Streaming tasks have no pre-registered returns: fail the
            # end-of-stream object so iterating generators surface the
            # error instead of waiting forever on the next item.
            from ray_tpu.core.streaming import stream_eos_id

            eos_hex = stream_eos_id(spec.task_id).hex()
            entry = self.objects.get(eos_hex)
            if entry is None or entry.state == PENDING:
                self._store_object_locked(
                    eos_hex, inline=data, size=len(data), is_error=True)

    # ------------------------------------------------------------------
    # Scheduler (counterpart of ClusterTaskManager::ScheduleAndDispatchTasks)
    def _next_wake_timeout(self) -> float:
        """How long the scheduler may park with no explicit wake.
        Short (0.5 s) only while time-driven state machines are live —
        starting workers, node drains, queued actors/PGs, deferred
        tasks; the watchdog interval when enabled; else the idle
        ceiling (RAY_TPU_SCHED_IDLE_WAIT_S).  Queued-lease expiry is
        armed on the timer wheel, so wakeups are O(pending timers)
        rather than O(polls)."""
        if self._ingress_pending():
            return 0.0  # submissions already staged: pass immediately
        with self.lock:
            busy = bool(
                self.pending_tasks
                or self.pending_actors
                or self._drain_migrating
                or any(pg.state == "PENDING"
                       for pg in self.placement_groups.values())
                or any(w.state == "starting"
                       for w in self.workers.values()))
            lease_deadline = self.pending_leases.earliest_deadline()
        if lease_deadline is not None:
            self._arm_lease_timer(lease_deadline)
        if busy:
            return 0.5
        if self._watchdog is not None:
            return min(self._idle_wait_s,
                       max(0.5, self._watchdog.interval_s))
        return self._idle_wait_s

    def _arm_lease_timer(self, deadline: float):
        """One wheel timer covers the earliest queued-lease expiry;
        re-armed only when the deadline moves earlier or the old timer
        already fired."""
        t = self._lease_timer
        now = time.time()
        if t is not None and not t.cancelled and t.deadline > now \
                and t.deadline <= deadline + 0.05:
            return
        if t is not None:
            t.cancel()
        from ray_tpu.util import timer_wheel

        self._lease_timer = timer_wheel.wheel().schedule(
            max(0.0, deadline - now) + 0.01, self._wake.set,
            label="lease_expiry")

    def _schedule_loop(self):
        while not self._stopped.is_set():
            self._wake.wait(timeout=self._next_wake_timeout())
            self._wake.clear()
            if self._stopped.is_set():
                return
            try:
                self._schedule_once()
            except Exception:
                import traceback

                traceback.print_exc()
            try:
                self._check_drains()
            except Exception:
                import traceback

                traceback.print_exc()
            try:
                self._sync_resource_view()
            except Exception:
                pass
            # Health watchdog: when disabled the object is None and
            # this gate is the hot path's ONLY trace of it; when
            # enabled, maybe_tick self-rate-limits to its interval.
            if self._watchdog is not None:
                self._watchdog.maybe_tick()

    # -- resource-view sync (N8; reference common/ray_syncer/ -----------
    # ray_syncer.h:88 RESOURCE_VIEW stream).  The head is the view's
    # source of truth (it charges/releases all resources), so the sync
    # is a debounced head -> node-manager broadcast of per-node
    # availability; node managers serve it locally (cluster_view /
    # available_resources ops) so colocated workers' resource queries
    # and future local decisions need not transit the head.
    def _sync_resource_view(self):
        now = time.monotonic()
        if now - getattr(self, "_view_last_sync", 0.0) < 0.2:
            return
        with self.lock:
            view = {
                nid: {"total": n.total.to_dict(),
                      "available": n.available.to_dict(),
                      "alive": n.alive, "is_head": n.is_head,
                      "labels": dict(n.labels)}
                for nid, n in self.nodes.items()
            }
            targets = [n.conn for n in self.nodes.values()
                       if n.conn is not None and n.alive]
        self._view_last_sync = now
        if not targets:
            # Nothing listening: do NOT record the view as sent — a
            # manager joining later must still get the first broadcast.
            return
        if view == getattr(self, "_view_last", None):
            return
        self._view_last = view
        seq = self._view_seq = getattr(self, "_view_seq", 0) + 1
        # Epoch disambiguates head restarts: a restarted head's seq
        # counter restarts, and managers must not reject it as stale.
        epoch = getattr(self, "_view_epoch", None)
        if epoch is None:
            epoch = self._view_epoch = uuid.uuid4().hex[:12]
        msg = {"op": "resource_view", "seq": seq, "epoch": epoch,
               "nodes": view}
        for conn in targets:
            try:
                conn.push(msg)
            except Exception:
                pass  # node death handled by its disconnect

    def _deps_ready(self, spec: TaskSpec) -> bool:
        for arg in spec.args:
            if arg.is_ref:
                entry = self.objects.get(arg.object_hex)
                if entry is None or entry.state == PENDING:
                    return False
        return True

    # -- resource charge/release (node- or bundle-scoped) ---------------
    def _release(self, w: WorkerInfo):
        """Lock held. Return a worker's acquired resources to where they
        were charged (PG bundle, else its node)."""
        if w.acquired.is_empty():
            w.charge = ()
            return
        ch = w.charge
        acquired, w.acquired = w.acquired, ResourceSet()
        w.charge = ()
        if ch and ch[0] == "pg":
            pg = self.placement_groups.get(ch[1])
            if (pg is not None and pg.state == "CREATED"
                    and ch[2] < len(pg.bundles)):
                b = pg.bundles[ch[2]]
                b.available = b.available.add(acquired)
                return
            # PG gone: its reservation was partially returned at removal;
            # the in-use remainder goes back to the node now.
        node = self.nodes.get(w.node_id)
        if node is not None and node.alive:
            node.available = node.available.add(acquired)
            self._index_touch(node.node_id)

    def _utilization(self, node: NodeState,
                     avail: Optional[ResourceSet] = None) -> float:
        tot = node.total.to_dict()
        av = (node.available if avail is None else avail).to_dict()
        utils = [1.0 - av.get(k, 0.0) / v for k, v in tot.items() if v > 0]
        return max(utils, default=0.0)

    def _task_arg_bytes(self, spec) -> int:
        """Lock held.  Total READY bytes of the spec's ref args (the
        watchdog's straggler size-bucket input).  Captured at dispatch
        while the running task still pins its args; inline and
        still-pending args contribute nothing."""
        total = 0
        for arg in getattr(spec, "args", ()):
            if not getattr(arg, "is_ref", False):
                continue
            entry = self.objects.get(arg.object_hex)
            if entry is not None and entry.state == READY:
                total += entry.size or 0
        return total

    def _locality_bytes(self, spec) -> Dict[str, int]:
        """Lock held.  Bytes of the spec's shm ref args already resident
        on each node — primary copy or pulled replica, straight from the
        object directory (the reference's locality-aware lease policy
        consults its object directory the same way,
        locality_data_provider in lease_policy.cc).  Inline and
        still-pending args contribute nothing."""
        out: Dict[str, int] = {}
        now = time.time()
        for arg in getattr(spec, "args", ()):
            if not getattr(arg, "is_ref", False):
                continue
            entry = self.objects.get(arg.object_hex)
            if entry is None or entry.state != READY or not entry.in_shm:
                continue
            locs = {entry.node_id, *entry.replicas}
            if entry.pulling:
                # Credit in-flight pull destinations too (the transfer
                # will land before or with the task); drop announces
                # older than the pull deadline — that pull failed.
                stale = [nid for nid, ts in entry.pulling.items()
                         if now - ts > 150.0]
                for nid in stale:
                    del entry.pulling[nid]
                locs.update(entry.pulling)
            for nid in locs:
                out[nid] = out.get(nid, 0) + entry.size
        return out

    @staticmethod
    def _locality_enabled() -> bool:
        return os.environ.get("RAY_TPU_NO_LOCALITY", "").strip().lower() \
            not in ("1", "true", "yes")

    def _pick_node(self, need: ResourceSet, spec,
                   avail_of=None) -> Optional[tuple]:
        """Lock held. Choose a node (or PG bundle) for this task/actor.

        Returns (node_id, charge_tuple) or None if nothing is feasible now.
        `avail_of(charge) -> ResourceSet` overrides the availability view —
        the task loop passes its *virtual* view (actual minus claims of
        still-pending tasks) so a saturated head spills work to other nodes
        instead of queueing everything on the packed node.
        Policy parity: hybrid pack-then-spread default
        (scheduling/policy/hybrid_scheduling_policy.h:50), SPREAD
        round-robin, node-affinity, PG bundles (bundle_pack/spread)."""
        if avail_of is None:
            avail_of = self._charge_avail
        # Placement-group bundle placement
        pg_hex = getattr(spec, "placement_group_hex", "")
        if pg_hex:
            pg = self.placement_groups.get(pg_hex)
            if pg is None or pg.state != "CREATED":
                return None
            indices = ([spec.bundle_index] if spec.bundle_index >= 0
                       else range(len(pg.bundles)))
            for i in indices:
                if i >= len(pg.bundles):
                    return None
                b = pg.bundles[i]
                node = self.nodes.get(b.node_id)
                if (node is not None and node.schedulable
                        and need.is_subset_of(avail_of(("pg", pg_hex, i)))):
                    return b.node_id, ("pg", pg_hex, i)
            return None

        def node_avail(n):
            return avail_of(("node", n.node_id))

        st = getattr(spec, "scheduling_strategy", None)
        if st is not None and type(st).__name__ == "NodeAffinitySchedulingStrategy":
            node = self.nodes.get(st.node_id)
            if (node is not None and node.schedulable
                    and need.is_subset_of(node_avail(node))):
                return node.node_id, ("node", node.node_id)
            if not st.soft:
                return None
            # soft: fall through to default policy
        idx = getattr(self, "_node_index", None)
        alive = [n for n in self.nodes.values() if n.schedulable] \
            if (idx is None
                or (st is not None
                    and type(st).__name__
                    == "NodeLabelSchedulingStrategy")) else []
        if st is not None and \
                type(st).__name__ == "NodeLabelSchedulingStrategy":
            hard = st.hard or {}
            soft = st.soft or {}

            def match(n, req):
                return all(n.labels.get(k) == v for k, v in req.items())

            labeled = [n for n in alive if match(n, hard)]
            pool = [n for n in labeled if match(n, soft)] if soft \
                else labeled
            feasible = [n for n in pool
                        if need.is_subset_of(node_avail(n))]
            if soft and not feasible:
                # Soft preference exhausted: any hard-matching node.
                feasible = [n for n in labeled
                            if need.is_subset_of(node_avail(n))]
            if not feasible:
                return None  # pending until a hard match has capacity
            node = min(feasible, key=lambda n: (
                self._utilization(n, node_avail(n)), n.node_id))
            return node.node_id, ("node", node.node_id)
        if idx is not None:
            # Utilization-bucketed candidate walk: O(1) amortized per
            # pick instead of an O(nodes) feasibility prefilter + sort.
            return self._pick_node_indexed(need, spec, st, node_avail)
        feasible = [n for n in alive if need.is_subset_of(node_avail(n))]
        if not feasible:
            return None

        def util(n):
            return self._utilization(n, node_avail(n))

        if st == "SPREAD":
            # least-utilized first; rotate among the tied minimum so
            # zero-resource tasks still fan out across nodes.  The tie-break
            # hashes the task id (not a global counter) so a task's target is
            # stable across scheduling passes while it waits for a worker.
            feasible.sort(key=lambda n: (util(n), n.node_id))
            lowest = util(feasible[0])
            ties = [n for n in feasible if util(n) == lowest]
            tid = getattr(spec, "task_id", None) or spec.actor_id
            # hash() (not a raw prefix slice): ids are counter-derived,
            # so any fixed byte slice can alias mod len(ties).
            node = ties[hash(tid.binary()) % len(ties)]
            return node.node_id, ("node", node.node_id)
        # hybrid default: pack onto the busiest node below the spread
        # threshold; above it, spread to the least utilized.  Utilization
        # ties break by bytes of this task's shm args already resident on
        # the candidate (locality-aware placement, reference
        # lease_policy.cc LocalityAwareLeasePolicy) — feasibility always
        # dominates, so locality never overrides resources.  Env
        # RAY_TPU_NO_LOCALITY=1 restores the legacy tie-break exactly
        # (with no locality data both keys collapse to the old ones).
        threshold = 0.5
        loc = (self._locality_bytes(spec) if self._locality_enabled()
               else {})
        below = [n for n in feasible if util(n) < threshold]
        if below:
            node = max(below, key=lambda n: (util(n),
                                             loc.get(n.node_id, 0),
                                             n.is_head))
        else:
            node = min(feasible, key=lambda n: (util(n),
                                                -loc.get(n.node_id, 0),
                                                not n.is_head))
        if loc.get(node.node_id, 0) > 0:
            if self._m_locality_hits is not None:
                self._m_locality_hits.inc()
        return node.node_id, ("node", node.node_id)

    def _pick_node_indexed(self, need: ResourceSet, spec, st,
                           node_avail) -> Optional[tuple]:
        """Lock held.  `_pick_node`'s SPREAD/hybrid tail over the
        utilization-bucketed index.  Bucket membership is computed from
        ACTUAL availability; feasibility is re-verified against the
        caller's (possibly virtual) view on every candidate, so a stale
        bucket can only cost placement optimality within one 1/8
        utilization slice, never correctness.  The PR-3 locality
        tie-break becomes an index consult: the nodes already holding
        this task's shm args are checked directly (O(arg locations))
        before the bucket walk."""
        idx = self._node_index
        nodes = self.nodes

        def fits(nid):
            n = nodes.get(nid)
            return (n is not None and n.schedulable
                    and need.is_subset_of(node_avail(n)))

        # Scarce-resource shortcut: when the ask names a resource only
        # a handful of nodes have free (TPU on a CPU-heavy cluster),
        # iterate that free set directly.
        res_names = [r for r, v in need.to_dict().items() if v > 0]
        scarce = idx.scarce_set(res_names) if res_names else None
        if scarce is not None:
            best, best_u = None, None
            for nid in scarce:
                if not fits(nid):
                    continue
                u = self._utilization(nodes[nid], node_avail(nodes[nid]))
                if best_u is None or u < best_u:
                    best, best_u = nid, u
            return (best, ("node", best)) if best is not None else None

        if st == "SPREAD":
            # Lowest non-empty utilization bucket = the tie set; the
            # task-id hash seeds the probe so a waiting task's target
            # is stable across passes while equal-utilization nodes
            # still fan out.
            tid = getattr(spec, "task_id", None) or spec.actor_id
            seed = hash(tid.binary())
            for bucket in idx.buckets_low_to_high():
                nid = idx.probe(bucket, seed, fits)
                if nid is not None:
                    return nid, ("node", nid)
            return None

        # hybrid pack-then-spread (threshold 0.5), locality consult
        # first: a fitting below-threshold node already holding the
        # most arg bytes wins outright.
        loc = (self._locality_bytes(spec) if self._locality_enabled()
               else {})
        if loc:
            best, best_bytes = None, 0
            for nid, nbytes in sorted(loc.items(),
                                      key=lambda kv: -kv[1]):
                if nbytes <= best_bytes or not fits(nid):
                    continue
                n = nodes[nid]
                if self._utilization(n, node_avail(n)) < 0.5:
                    best, best_bytes = nid, nbytes
            if best is not None:
                if self._m_locality_hits is not None:
                    try:
                        self._m_locality_hits.inc()
                    except Exception:  # raylint: allow-swallow(telemetry only)
                        pass
                return best, ("node", best)
        # pack: most-utilized bucket below the spread threshold first
        for bucket in idx.buckets_high_to_low(below=0.5):
            nid = idx.probe(bucket, 0, fits)
            if nid is not None:
                return nid, ("node", nid)
        # nothing below threshold fits: spread to the least utilized
        for bucket in idx.buckets_low_to_high():
            nid = idx.probe(bucket, 0, fits)
            if nid is not None:
                return nid, ("node", nid)
        return None

    def _unschedulable_reason(self, spec) -> Optional[str]:
        """Lock held. Non-None if the spec can NEVER schedule — removed PG,
        out-of-range bundle index, or hard node affinity to a dead/missing
        node.  The reference fails these fast with a scheduling error
        (TaskUnschedulableError) rather than pending forever."""
        pg_hex = getattr(spec, "placement_group_hex", "")
        if pg_hex:
            pg = self.placement_groups.get(pg_hex)
            if pg is None or pg.state == "REMOVED":
                return "placement group removed"
            bi = getattr(spec, "bundle_index", -1)
            if bi >= len(pg.bundle_specs):
                return (f"bundle index {bi} out of range "
                        f"(placement group has {len(pg.bundle_specs)})")
            return None
        st = getattr(spec, "scheduling_strategy", None)
        if (st is not None
                and type(st).__name__ == "NodeAffinitySchedulingStrategy"
                and not st.soft):
            node = self.nodes.get(st.node_id)
            if node is None or not node.alive:
                return f"node {st.node_id} is dead or does not exist"
        renv = getattr(spec, "runtime_env", None)
        if renv:
            key = self._env_key_for(spec.resources, renv)
            entry = self.broken_envs.get(key)
            if entry is not None:
                err, poisoned_at = entry
                if time.time() - poisoned_at <= self.broken_env_ttl_s:
                    return f"runtime_env setup failed: {err}"
                del self.broken_envs[key]  # expired: allow a fresh try
        return None

    def _index_touch(self, node_id: str):
        if self._node_index is not None:
            self._node_index.touch(node_id)

    def _index_rebuild(self):
        if self._node_index is not None:
            self._node_index.rebuild()

    def _charge_avail(self, charge: tuple) -> ResourceSet:
        """Lock held. Resolve a charge tuple to its current availability."""
        if charge[0] == "pg":
            pg = self.placement_groups.get(charge[1])
            return (pg.bundles[charge[2]].available
                    if pg is not None and charge[2] < len(pg.bundles)
                    else ResourceSet())
        node = self.nodes.get(charge[1])
        return node.available if node is not None else ResourceSet()

    def _charge_target_subtract(self, charge: tuple, need: ResourceSet):
        """Lock held."""
        if charge[0] == "pg":
            b = self.placement_groups[charge[1]].bundles[charge[2]]
            b.available = b.available.subtract(need)
        else:
            node = self.nodes[charge[1]]
            node.available = node.available.subtract(need)
            self._index_touch(charge[1])

    def _schedule_once(self):
        self._reap_unregistered_workers()
        with self.lock:
            self._drain_submit_ingress_locked()
            # 0. retry pending placement groups (resources may have freed or
            # nodes joined — reference GcsPlacementGroupManager retry loop)
            for pg in self.placement_groups.values():
                if pg.state == "PENDING":
                    self._try_reserve_pg(pg)

            # 1. actors first (they need fresh workers)
            still_pending_actors = []
            to_spawn = []
            for spec in self.pending_actors:
                need = ResourceSet(spec.resources)
                why = self._unschedulable_reason(spec)
                if why is not None:
                    entry = self.actors.get(spec.actor_id.hex())
                    if entry is not None:
                        entry.state = A_DEAD
                        entry.death_reason = why
                        self._push_actor_update(entry, spec.actor_id.hex())
                        self._fail_actor_inflight(spec.actor_id.hex(), why)
                    continue
                pick = self._pick_node(need, spec)
                if pick is None:
                    still_pending_actors.append(spec)
                    continue
                node_id, charge = pick
                self._charge_target_subtract(charge, need)
                to_spawn.append((spec, need, node_id, charge))
            self.pending_actors = still_pending_actors

            # 2. normal tasks to idle pool workers on their chosen node
            dispatches = []
            still_pending = []
            idle = {
                h: w for h, w in self.workers.items()
                if w.kind == "pool" and w.state == "idle" and w.conn is not None
            }
            # Per-node worker counts: max_workers_per_node caps each node's
            # pool, not the cluster (a full head must not starve new nodes).
            node_workers: Dict[str, int] = {}
            # Workers already starting, per (node, env_key): spawn only the
            # deficit (reference WorkerPool prestart accounting,
            # worker_pool.h:159).
            starting: Dict[tuple, int] = {}
            for w in self.workers.values():
                if w.kind == "pool" and w.state != "dead":
                    node_workers[w.node_id] = node_workers.get(
                        w.node_id, 0) + 1
                    if w.state == "starting":
                        key = (w.node_id, w.env_key)
                        starting[key] = starting.get(key, 0) + 1
            # Virtual availability per charge target (node or PG bundle):
            # resources that would be in use if every
            # dispatchable-but-workerless task had its worker.
            avail_virtual: Dict[tuple, ResourceSet] = {}

            def virt_get(charge):
                if charge not in avail_virtual:
                    avail_virtual[charge] = self._charge_avail(charge)
                return avail_virtual[charge]
            # A pass can place at most len(idle) tasks plus whatever new
            # workers could still spawn; once that budget is spent, the
            # rest of the queue cannot make progress THIS pass — bulk-
            # defer it instead of rescanning (keeps each wake O(capacity)
            # rather than O(pending), which made big async batches
            # quadratic: every task_done re-scanned the whole queue).
            spawn_headroom = sum(
                max(0, self.config.max_workers_per_node
                    - node_workers.get(nid, 0))
                for nid, node in self.nodes.items() if node.alive)
            budget = len(idle) + spawn_headroom
            progress = 0
            # Per-pass infeasibility memo: once a (resources, placement)
            # shape fails to place, identical later requests are skipped
            # in O(1). A saturated homogeneous queue (the common case:
            # thousands of same-shaped tasks) costs one real placement
            # attempt per pass instead of one per task — this is what
            # keeps big async batches from going quadratic.
            infeasible: set = set()

            def _shape_key(s):
                return (tuple(sorted(s.resources.items())),
                        s.placement_group_hex, s.bundle_index,
                        repr(s.scheduling_strategy))

            for spec in self.pending_tasks:
                if not self._deps_ready(spec):
                    still_pending.append(spec)
                    continue
                # The unschedulable fast-fail must run for EVERY ready
                # spec, even when the pass's placement budget is spent —
                # a removed-PG/dead-node task that merely stays pending
                # on a saturated cluster would deadlock its waiters.
                why = self._unschedulable_reason(spec)
                if why is not None:
                    rec = self.tasks.get(spec.task_id.hex())
                    if rec is not None:
                        rec.state = "FAILED"
                    self._fail_task_returns_with(
                        spec, why, kind="unschedulable")
                    continue
                shape = _shape_key(spec)
                if progress >= budget or shape in infeasible:
                    still_pending.append(spec)
                    continue
                need = ResourceSet(spec.resources)
                pick = self._pick_node(need, spec, avail_of=virt_get)
                if pick is None:
                    infeasible.add(shape)
                    still_pending.append(spec)
                    continue
                node_id, charge = pick
                env_key = self._env_key_for(spec.resources, spec.runtime_env)
                worker = next(
                    (w for w in idle.values()
                     if w.env_key == env_key and w.node_id == node_id), None)
                if worker is None:
                    virt = virt_get(charge)
                    if need.is_subset_of(virt):
                        avail_virtual[charge] = virt.subtract(need)
                        key = (node_id, env_key)
                        if starting.get(key, 0) > 0:
                            starting[key] -= 1  # one already on the way
                            progress += 1  # a worker really is incoming
                        elif (node_workers.get(node_id, 0)
                                < self.config.max_workers_per_node):
                            self._spawn_worker(env_key=env_key, kind="pool",
                                               node_id=node_id)
                            node_workers[node_id] = node_workers.get(
                                node_id, 0) + 1
                            progress += 1
                    still_pending.append(spec)
                    continue
                del idle[worker.worker_hex]
                virt = virt_get(charge)  # snapshot BEFORE charging
                self._charge_target_subtract(charge, need)
                if need.is_subset_of(virt):
                    avail_virtual[charge] = virt.subtract(need)
                worker.acquired = need
                worker.charge = charge
                worker.state = "busy"
                worker.current_task = spec.task_id.hex()
                rec = self.tasks.get(spec.task_id.hex())
                if rec is not None:
                    rec.state = "RUNNING"
                    rec.worker_hex = worker.worker_hex
                    rec.started_at = time.time()
                    rec.arg_bytes = self._task_arg_bytes(spec)
                dispatches.append((worker, spec))
                progress += 1
            self.pending_tasks = still_pending

            for spec, need, node_id, charge in to_spawn:
                w = self._spawn_worker(
                    env_key=self._env_key_for(spec.resources, spec.runtime_env),
                    kind="actor", node_id=node_id)
                w.acquired = need
                w.charge = charge
                w.actor_hex = spec.actor_id.hex()
                entry = self.actors.get(spec.actor_id.hex())
                if entry is not None:
                    entry.worker_hex = w.worker_hex
                # queue the creation spec; delivered when the worker registers
                w.pending_create = spec  # type: ignore[attr-defined]

            # 3. queued lease requests take what's left (tasks/actors
            # queued at the head go first — they were already waiting).
            lease_grants = self._try_grant_leases_locked()

        if lease_grants:
            self._push_lease_grants(lease_grants)
        for worker, spec in dispatches:
            try:
                worker.conn.push({"op": "execute_task", "spec": spec})
            except Exception:
                with self.lock:
                    self._mark_worker_dead(worker, "push failed")

    def _env_key_for(self, resources: Dict[str, float],
                     runtime_env: Optional[dict]) -> str:
        tpu = resources.get(TPU, 0) if resources else 0
        env_part = ""
        if runtime_env:
            import hashlib
            import json

            env_part = hashlib.sha1(
                json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:8]
        key = f"tpu{int(tpu)}-{env_part}"
        if runtime_env:
            self.runtime_envs.setdefault(key, dict(runtime_env))
        return key

    def _op_free_stream(self, conn, msg):
        """Release a dropped ObjectRefGenerator's unconsumed items (and
        its eos object if the consumer never read it). Only acts on
        finished streams — a live one still needs its slots."""
        from ray_tpu.core.serialization import deserialize
        from ray_tpu.core.streaming import stream_eos_id, stream_item_id
        from ray_tpu.core.ids import TaskID

        task_id = TaskID.from_hex(msg["task"])
        eos_hex = stream_eos_id(task_id).hex()
        start = int(msg.get("from_index", 0))
        known_count = msg.get("count")
        if known_count is not None:
            # The consumer read the EOS (whose decref may already have
            # deleted it here): free directly from the count it learned
            # — no EOS lookup, no parking.
            targets = [stream_item_id(task_id, i).hex()
                       for i in range(start, int(known_count))]
            if not msg.get("eos_consumed", False):
                targets.append(eos_hex)
            for obj_hex in targets:
                self._op_decref(conn, {"obj": obj_hex})
            return
        with self.lock:
            eos = self.objects.get(eos_hex)
            if eos is None or eos.state == PENDING:
                # Stream still running (or its EOS put is still in
                # flight — item puts and the EOS are separate frames, so
                # a consumer can observe the tail item and drop the
                # generator before the EOS lands): park the free and
                # apply it when the EOS stores (_store_object_locked).
                # A CONSUMED EOS (the normal fully-drained lifecycle)
                # was decref-deleted and will never store again — there
                # is nothing left to free, so parking it would leak one
                # entry per drained stream.
                if not msg.get("eos_consumed", False):
                    frees = getattr(self, "_pending_stream_frees", None)
                    if frees is None:
                        frees = self._pending_stream_frees = {}
                    if len(frees) >= 4096:  # bound pathological growth
                        frees.pop(next(iter(frees)))
                    frees[eos_hex] = dict(msg)
                return
            count = None
            if eos.state == READY and eos.inline is not None:
                try:
                    count = int(deserialize(eos.inline))
                except Exception:
                    count = None
            if count is None:
                # ERRORED EOS (producer died mid-stream) carries no item
                # count: probe a bounded id range — decref no-ops on
                # ids that were never stored.
                count = start + 4096
            targets = [stream_item_id(task_id, i).hex()
                       for i in range(start, count)]
            if not msg.get("eos_consumed", False):
                targets.append(eos_hex)
        for obj_hex in targets:
            self._op_decref(conn, {"obj": obj_hex})

    # -- cross-node object plane ---------------------------------------
    def _node_client(self, node_id: str) -> Optional[rpc.Client]:
        """Head-side rpc client to a node manager's object server."""
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive or not node.address:
                return None
            address = node.address
        clients = getattr(self, "_node_clients", None)
        if clients is None:
            clients = self._node_clients = {}
        client = clients.get(address)
        if client is None or client._closed:
            try:
                client = rpc.Client(address, connect_timeout=2.0)
            except Exception:
                return None
            racer = clients.setdefault(address, client)
            if racer is not client:  # another handler dialed first
                if racer._closed:
                    clients[address] = client
                else:
                    client.close()
                    client = racer
        return client

    def _pull_node_object(self, node_id: str, obj_hex: str,
                          size: int) -> Optional[bytes]:
        """Pull a whole object's bytes from its holding node (chunked,
        windowed like every other puller)."""
        client = self._node_client(node_id)
        if client is None:
            return None
        try:
            return rpc.pull_object_chunked(
                client, obj_hex, size, self.config.transfer_chunk_bytes,
                window=self.config.pull_window)
        except Exception:
            return None

    def _op_fetch_chunk(self, conn, msg):
        """Serve one chunk of a head-arena object to a remote puller
        (reference ObjectManager chunked Push/Pull,
        object_manager.h:206/:139).  The attach stays cached in the
        store until the object is deleted, so concurrent chunk reads of
        one object never race a release."""
        obj_hex = msg["obj"]
        with self.lock:
            entry = self.objects.get(obj_hex)
            node_loc = entry.node_id if entry is not None else "head"
        if entry is None:
            return None
        if node_loc != "head":
            # Rare proxy case (location moved between the client's info
            # snapshot and this request): pull-through from the real
            # node, caching the payload so the client's REMAINING chunk
            # requests for this object don't each re-pull the whole
            # thing (one-entry cache; the window is one transfer).
            with self.lock:
                cached = getattr(self, "_proxy_cache", None)
            if cached is None or cached[0] != obj_hex:
                data = self._pull_node_object(node_loc, obj_hex,
                                              msg["size"])
                if data is None:
                    return None
                with self.lock:
                    self._proxy_cache = (obj_hex, data)
                cached = (obj_hex, data)
            part = cached[1][msg["offset"]:msg["offset"] + msg["length"]]
            if msg["offset"] + msg["length"] >= msg["size"]:
                # Final chunk served: drop the (potentially 100s-of-MB)
                # payload instead of pinning it in head memory until the
                # next proxy pull happens to evict it.
                with self.lock:
                    if getattr(self, "_proxy_cache", None) is not None \
                            and self._proxy_cache[0] == obj_hex:
                        self._proxy_cache = None
            object_plane.OBJ._inc("bytes_pushed", len(part))
            return part
        seg = self.store.attach(ObjectID.from_hex(obj_hex), msg["size"])
        off, n = msg["offset"], msg["length"]
        part = bytes(seg.buf[off:off + n])
        object_plane.OBJ._inc("bytes_pushed", len(part))
        return part

    def _op_report_object_lost(self, conn, msg):
        """A client's pull from a remote node failed (the node's arena
        evicted/lost the copy while the node itself stays alive): verify
        with the node and fall back to lineage reconstruction — the
        remote-arena counterpart of the head's _shm_value_lost probe."""
        obj_hex = msg["obj"]
        with self.lock:
            entry = self.objects.get(obj_hex)
            if entry is None or not entry.in_shm or entry.restoring \
                    or entry.node_id == "head" or entry.state != READY:
                return False
            node_loc = entry.node_id
        client = self._node_client(node_loc)
        if client is not None:
            try:
                if client.call({"op": "has_object", "obj": obj_hex},
                               timeout=5.0):
                    return False  # still there; the pull failure was racy
            except Exception:
                pass  # node unreachable: treat as lost
        with self.lock:
            entry = self.objects.get(obj_hex)
            if entry is None or not entry.in_shm \
                    or entry.node_id != node_loc or entry.state != READY:
                return False
            entry.in_shm = False
            if not self._try_reconstruct_locked(obj_hex):
                self._store_lost_error_locked(
                    obj_hex, f"copy on node {node_loc} is gone and "
                    "lineage reconstruction was not possible")
        self._wake.set()
        return True

    def _op_object_shm_info(self, conn, msg):
        """Where a same-host native client can map an object zero-copy
        (the reference's plasma C++ client attach path: cpp frontends
        read sealed objects straight from the arena instead of proxying
        payloads through the server — object_manager/plasma/).  Replies
        with the head arena + store library paths only when the object's
        authoritative copy lives in the head arena; everything else is
        "not mappable here" and callers fall back to fetch_object."""
        obj_hex = msg["obj"]
        with self.lock:
            entry = self.objects.get(obj_hex)
            if entry is None or entry.state not in (READY, ERRORED) \
                    or not entry.in_shm or entry.spilled_uri is not None \
                    or entry.node_id != "head":
                return {"in_shm": False}
            size = entry.size
            is_error = entry.is_error
        arena = getattr(self.store, "_arena", None)
        if arena is None:
            return {"in_shm": False}  # file-per-object fallback store
        try:
            from ray_tpu.native.store import library_path

            lib = library_path()
        except Exception:
            # No loadable store library -> the client cannot attach;
            # answer "not mappable" so it falls back to fetch_object.
            return {"in_shm": False}
        return {"in_shm": True, "arena": arena.path, "lib": lib,
                "size": size, "is_error": is_error}

    def _op_fetch_object(self, conn, msg):
        """Read an object's payload server-side for thin clients (no shm
        attachment — reference Ray Client server proxy role). Shm reads
        and spilled-object restores happen outside the lock."""
        obj_hex = msg["obj"]
        # with_meta callers get {"data", "is_error"} so they never rely on
        # a stale error flag cached before a reconstruction/lost event.
        with_meta = bool(msg.get("with_meta"))

        def reply(data, is_error):
            return {"data": data, "is_error": is_error} if with_meta \
                else data

        # Retry loop: the object can migrate between shm and external
        # storage (spill / concurrent restore) between the snapshot and
        # the read; re-reading the entry makes the race benign.
        for attempt in range(4):
            with self.lock:
                entry = self._object_entry_or_drain_locked(obj_hex)
                if entry is None or entry.state not in (READY, ERRORED):
                    return None
                if entry.inline is not None:
                    return reply(entry.inline, entry.is_error)
                size = entry.size
                spilled_uri = entry.spilled_uri
                is_error = entry.is_error
                node_loc = entry.node_id
            if spilled_uri is None and node_loc != "head":
                # Copy lives in a remote node's arena: pull it over the
                # object plane.  A failed pull means the node just died —
                # _handle_node_death kicks reconstruction; wait and retry.
                data = self._pull_node_object(node_loc, obj_hex, size)
                if data is not None:
                    return reply(data, is_error)
                self._await_object_settled(obj_hex, 30.0)
                continue
            if spilled_uri is not None:
                try:
                    return reply(self.external_storage.restore(spilled_uri),
                                 is_error)
                except Exception:
                    # Restored+deleted meanwhile (benign race) — or the
                    # spilled copy itself is gone; mirror
                    # _restore_and_publish: reconstruct from lineage or
                    # materialize the lost error, then wait it out.
                    with self.lock:
                        entry = self.objects.get(obj_hex)
                        if entry is not None \
                                and entry.spilled_uri == spilled_uri \
                                and not entry.restoring:
                            entry.spilled_uri = None
                            if not self._try_reconstruct_locked(obj_hex):
                                self._store_lost_error_locked(
                                    obj_hex, "spilled copy unreadable and "
                                    "lineage reconstruction not possible")
                    self._await_object_settled(obj_hex, 30.0)
                    continue
            try:
                oid = ObjectID.from_hex(obj_hex)
                seg = self.store.attach(oid, size)
                data = bytes(seg.buf[:size])
                self.store.release(oid)
                return reply(data, is_error)
            except Exception:
                # Spilled meanwhile (re-snapshot) — or the copy is gone,
                # in which case kick lineage reconstruction and wait for
                # the re-run to store the value; when reconstruction is
                # impossible, materialize ObjectLostError so this (and
                # every later) read returns the same error the subscribe
                # path serves.
                with self.lock:
                    entry = self.objects.get(obj_hex)
                    if entry is not None and \
                            self._shm_value_lost(obj_hex, entry):
                        if not self._try_reconstruct_locked(obj_hex):
                            self._store_lost_error_locked(
                                obj_hex, "shm copy gone and lineage "
                                "reconstruction not possible")
                self._await_object_settled(obj_hex, 30.0)
        return None

    def _await_object_settled(self, obj_hex: str, timeout: float) -> None:
        """Block until an object is READY/ERRORED and not mid-restore —
        i.e. until a kicked reconstruction/restore lands.  Event-driven:
        _store_object_locked and restore completion notify the settle
        condition, so waiters wake on the transition itself (the 1 s
        re-check only guards entry deletion, which doesn't notify)."""
        deadline = time.time() + timeout
        with self._obj_settled:
            while True:
                entry = self.objects.get(obj_hex)
                if entry is None:
                    return
                if entry.state in (READY, ERRORED) and \
                        not entry.restoring:
                    return
                remaining = deadline - time.time()
                if remaining <= 0:
                    return
                self._obj_settled.wait(min(remaining, 1.0))

    # ------------------------------------------------------------------
    # On-demand worker profiling (reference: dashboard reporter
    # profile_manager.py py-spy/memray drivers; TPU-native addition per
    # SURVEY.md §5: jax.profiler traces of live workers)
    def _op_profile_worker(self, conn, msg):
        """Ask a live worker for a profile; the reply resolves a
        Deferred so the CALLER's connection thread is never blocked (its
        other in-flight control calls proceed during a long trace).
        kind: 'stack' (all-thread dump) | 'jax_trace' (xplane dir)."""
        worker_hex = msg["worker_hex"]
        timeout = float(msg.get("timeout_s", 0) or
                        (float(msg.get("duration_s", 2.0)) + 30.0))
        with self.lock:
            w = self.workers.get(worker_hex)
            if w is None or w.conn is None or w.state == "dead":
                raise ValueError(f"no live worker {worker_hex}")
            if w.conn is conn:
                # The reply would arrive on THIS connection, inside the
                # request the target would have to answer. Callers
                # profile themselves locally (state/api.py shortcut).
                raise ValueError(
                    "cannot profile the requesting process through the "
                    "control plane; take the dump locally")
            token = uuid.uuid4().hex
            deferred = rpc.Deferred()

            def on_timeout():
                entry = self._profile_waiters.pop(token, None)
                if entry is not None:
                    entry[0].reject(TimeoutError(
                        f"worker {worker_hex} did not reply to profile "
                        f"request within {timeout:.0f}s"))

            timer = threading.Timer(timeout, on_timeout)
            timer.daemon = True
            if not hasattr(self, "_profile_waiters"):
                self._profile_waiters = {}
            # Register BEFORE the push: a fast worker's reply must find
            # the waiter.
            self._profile_waiters[token] = (deferred, timer)
            w.conn.push({"op": "profile", "token": token,
                         "kind": msg.get("kind", "stack"),
                         "duration_s": float(msg.get("duration_s", 2.0))})
        timer.start()
        return deferred

    def _op_profile_result(self, conn, msg):
        entry = getattr(self, "_profile_waiters", {}).pop(
            msg.get("token"), None)
        if entry is not None:
            deferred, timer = entry
            timer.cancel()  # don't park a thread for the full timeout
            deferred.resolve(msg.get("data"))

    # ------------------------------------------------------------------
    # Cluster-wide span harvest (collect_spans wire op): the head pulls
    # each worker's bounded span ring incrementally — per-worker cursors
    # persist across harvests, each reply is capped so a 100k ring
    # streams out as many small frames — and accumulates the result in
    # a bounded trace_id-queryable store (the /api/spans and /api/trace
    # backing data).
    def _op_harvest_spans(self, conn, msg):
        """Harvest every live worker's ring, then return matching spans.
        Runs on its own thread behind a Deferred: the multi-round
        pull protocol must not park the caller's connection thread."""
        deferred = rpc.Deferred()

        def run():
            try:
                deferred.resolve(self._harvest_spans_sync(msg))
            except Exception as e:  # noqa: BLE001
                deferred.reject(e)

        threading.Thread(target=run, name="span-harvest",
                         daemon=True).start()
        return deferred

    def _harvest_spans_sync(self, msg) -> Dict[str, Any]:
        timeout_s = float(msg.get("timeout_s", 0) or 10.0)
        deadline = time.monotonic() + timeout_s
        since = float(msg.get("since", 0) or 0.0)
        # poll=False answers from the store alone (no worker round
        # trips) — the restart-replay read path, where the store was
        # rehydrated from the journal and the old workers are gone.
        do_poll = msg.get("poll")
        do_poll = True if do_poll is None else bool(do_poll)
        if do_poll:
            with self._harvest_lock:  # serialize: cursors shared state
                polled = self._harvest_all_workers(deadline)
        else:
            polled = 0
        trace_id = msg.get("trace_id") or ""
        max_spans = int(msg.get("max_spans", 0) or 0)
        with self._span_lock:
            missed = self._span_missed
            if not trace_id and not since and max_spans > 0:
                # Bounded tail without copying the whole store — the
                # 1 Hz-poller shape, where reply size is the cost.
                start = max(0, len(self._span_store) - max_spans)
                rows = list(itertools.islice(
                    self._span_store, start, len(self._span_store)))
            else:
                rows = list(self._span_store)
        if trace_id:
            rows = [r for r in rows if r[2] == trace_id]
        if since:
            # Time window: keep spans still running at `since` or ended
            # after it (row[5] is the span end timestamp).
            rows = [r for r in rows if r[5] >= since]
        if max_spans > 0:
            rows = rows[-max_spans:]
        # The store keeps compact collect_spans rows; only the reply —
        # already bounded — pays for dict expansion.
        from ray_tpu.util.tracing import span_row_to_dict

        spans = [span_row_to_dict(r) for r in rows]
        return {"spans": spans, "workers_polled": polled,
                "missed": missed}

    def _harvest_all_workers(self, deadline: float) -> int:
        limit = _env_int("RAY_TPU_SPAN_HARVEST_CHUNK", 2048, 16)
        with self.lock:
            targets = [(wh, w.conn) for wh, w in self.workers.items()
                       if w.conn is not None and w.state != "dead"]
        polled = 0
        for worker_hex, wconn in targets:
            try:
                if self._harvest_one_worker(worker_hex, wconn, limit,
                                            deadline):
                    polled += 1
            except Exception:
                continue  # worker died mid-harvest; others still count
        return polled

    def _harvest_one_worker(self, worker_hex: str, wconn, limit: int,
                            deadline: float) -> bool:
        cursor = self._span_cursors.get(worker_hex, 0)
        replied = False
        # Per-sweep work bound: a worker emitting spans faster than the
        # sweep cadence can drain them must not turn one harvest into an
        # unbounded pull — the cursor persists, the next sweep continues
        # where this one stopped, and if the ring laps the cursor in the
        # meantime the worker reports it as `missed` (graceful data loss
        # over unbounded harvest CPU).
        max_chunks = _env_int("RAY_TPU_SPAN_HARVEST_MAX_CHUNKS", 8, 1)
        rounds = 0
        while rounds < max_chunks:
            rounds += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            token = uuid.uuid4().hex
            ev = threading.Event()
            slot: Dict[str, Any] = {}
            # Register BEFORE the push (profile-waiter discipline).
            self._span_waiters[token] = (ev, slot)
            try:
                wconn.push({"op": "collect_spans", "token": token,
                            "cursor": cursor, "limit": limit})
            except Exception:
                self._span_waiters.pop(token, None)
                break
            if not ev.wait(timeout=min(remaining, 5.0)):
                self._span_waiters.pop(token, None)
                break
            reply = slot.get("msg") or {}
            replied = True
            cursor = int(reply.get("cursor", cursor) or 0)
            rows = reply.get("rows") or []
            self._ingest_spans(worker_hex, reply, rows)
            if len(rows) < limit:
                break  # ring drained
        self._span_cursors[worker_hex] = cursor
        return replied

    def _ingest_spans(self, worker_hex: str, reply: dict,
                      rows: List[list]) -> None:
        """Fold one collect_spans reply into the store, keeping the
        compact row form — (span_id, parent_id, trace_id, name, start,
        end, attrs, worker, pid) — so a high-rate harvest costs list
        appends, not 7-key dict builds per span (expansion is deferred
        to the bounded _harvest_spans_sync reply)."""
        pid = int(reply.get("pid") or 0)
        missed = int(reply.get("missed") or 0)
        added: List[list] = []
        with self._span_lock:
            for r in rows:
                sid = r[0]
                if sid in self._span_seen:
                    continue
                r.append(worker_hex)
                r.append(pid)
                if len(self._span_store) == self._span_store.maxlen \
                        and self._span_store:
                    self._span_seen.discard(self._span_store[0][0])
                self._span_seen.add(sid)
                self._span_store.append(r)
                added.append(r)
            if missed:
                self._span_missed += missed
        # Durable spill (outside the lock; append is an enqueue — the
        # journal's writer thread owns all disk IO).  The _span_seen
        # dedup above also keeps a post-restart re-harvest from
        # re-journaling rows the journal already holds.
        if added:
            from ray_tpu.util import journal as ops_journal

            j = ops_journal.stream("spans")
            if j is not None:
                for r in added:
                    j.append(r)

    def _op_collect_spans_result(self, conn, msg):
        """One-way reply from a worker's collect_spans push: hand the
        payload to the waiting harvest round by token."""
        entry = self._span_waiters.pop(msg.get("token"), None)
        if entry is not None:
            ev, slot = entry
            slot["msg"] = msg
            ev.set()

    # ------------------------------------------------------------------
    # Per-worker resource profiling (profile_report deltas riding the
    # coalescing flusher) + watchdog introspection.
    def _op_profile_report(self, conn, msg):
        sample = msg.get("sample") or {}
        whex = sample.get("worker") or \
            getattr(conn, "meta", {}).get("worker_hex", "")
        if whex:
            with self.lock:
                self._profiles[whex] = sample
                ring = self._profile_hist.get(whex)
                if ring is None:
                    ring = self._profile_hist[whex] = deque(
                        maxlen=self._profile_hist_cap)
                ring.append(sample)

    def _op_get_profile(self, conn, msg):
        with self.lock:
            live = {wh for wh in self._profiles
                    if wh in self.workers
                    and self.workers[wh].state != "dead"}
            profiles = {wh: s for wh, s in self._profiles.items()
                        if wh in live}
            rings = {wh: list(ring)
                     for wh, ring in self._profile_hist.items()
                     if wh in live}
        history = {wh: _profile_history_summary(samples)
                   for wh, samples in rings.items()}
        if msg.get("samples"):
            for wh, summary in history.items():
                summary["raw"] = rings[wh]
        wd = (self._watchdog.snapshot() if self._watchdog is not None
              else {"enabled": False})
        return {"workers": profiles, "history": history,
                "history_capacity": self._profile_hist_cap,
                "watchdog": wd}

    def _op_set_profile_config(self, conn, msg):
        """Retune every live worker's resource sampler at runtime (the
        bench's A/B switch; also an operator knob for incident-time
        high-frequency sampling)."""
        cfg: Dict[str, Any] = {"op": "profile_config"}
        if msg.get("enabled") is not None:
            cfg["enabled"] = bool(msg["enabled"])
        if msg.get("interval_s") is not None:
            cfg["interval_s"] = float(msg["interval_s"])
        with self.lock:
            conns = [w.conn for w in self.workers.values()
                     if w.conn is not None and w.state != "dead"]
        notified = 0
        for c in conns:
            try:
                c.push(dict(cfg))
                notified += 1
            except Exception:
                pass
        return {"notified": notified}

    def _op_get_runtime_env(self, conn, msg):
        with self.lock:
            return self.runtime_envs.get(msg.get("env_key", ""))

    def _op_worker_setup_failed(self, conn, msg):
        """A worker's runtime-env setup raised: poison the env so pending
        and future work needing it fails fast (the worker exits itself)."""
        env_key = msg.get("env_key", "")
        error = msg.get("error", "runtime_env setup failed")
        with self.lock:
            self.broken_envs[env_key] = (error, time.time())
        self._wake.set()
        return True

    # ------------------------------------------------------------------
    # Worker pool (counterpart of raylet WorkerPool::StartWorkerProcess)
    def _spawn_worker(self, env_key: str, kind: str,
                      node_id: str = "head") -> WorkerInfo:
        """Lock held.  Local nodes fork the process here; remote nodes
        get a spawn_worker push to their manager (reference: the raylet
        owns worker processes on its host, worker_pool.h:159)."""
        from ray_tpu.core.node_manager import spawn_worker_process

        worker_id = WorkerID.from_random()
        w = WorkerInfo(worker_hex=worker_id.hex(), kind=kind, env_key=env_key,
                       state="starting", node_id=node_id,
                       spawned_at=time.time())
        self.workers[worker_id.hex()] = w
        renv = self.runtime_envs.get(env_key)
        node = self.nodes.get(node_id)
        if node is not None and node.conn is not None:
            try:
                node.conn.push({
                    "op": "spawn_worker", "worker_hex": worker_id.hex(),
                    "kind": kind, "env_key": env_key,
                    "namespace": self.namespace,
                    # The container wrapper applies at SPAWN on the
                    # worker's own host (runtime_env/container.py).
                    "runtime_env": renv})
            except Exception:
                self._mark_worker_dead(w, "node manager unreachable")
            return w
        proc = spawn_worker_process(
            control_addr=self.address, worker_hex=worker_id.hex(),
            kind=kind, env_key=env_key, namespace=self.namespace,
            node_id=node_id,
            log_dir=os.path.join(self.session_dir, "logs"),
            session_id=self.session_id, runtime_env=renv)
        w.proc = proc
        w.pid = proc.pid
        return w

    def _reap_unregistered_workers(self):
        """A spawned worker that never registered within the timeout
        (its process died pre-registration, or its node crashed
        mid-spawn) will produce no disconnect event — observe the death
        here so its task/actor is retried instead of hanging.  Takes
        and releases the lock itself (remote liveness probes must not
        run under it)."""
        timeout = self.config.worker_register_timeout_s
        if timeout <= 0:
            return
        now = time.time()
        remote_suspects = []
        with self.lock:
            for w in list(self.workers.values()):
                if w.state != "starting" or w.conn is not None:
                    continue
                if not w.spawned_at or now - w.spawned_at < timeout:
                    continue
                if w.proc is not None:
                    if w.proc.poll() is None:
                        continue  # local process still alive (slow import)
                    self._mark_worker_dead(w, "worker never registered")
                else:
                    remote_suspects.append(w)
        # Remote workers get the same tolerance as slow local imports:
        # ask their node manager whether the process is still alive.
        for w in remote_suspects:
            alive = False
            client = self._node_client(w.node_id)
            if client is not None:
                try:
                    alive = bool(client.call(
                        {"op": "worker_alive", "worker_hex": w.worker_hex},
                        timeout=5.0))
                except Exception:
                    alive = False
            if alive:
                continue
            with self.lock:
                if w.state == "starting" and w.conn is None:
                    self._mark_worker_dead(w, "worker never registered")

    def deliver_pending_create(self, w: WorkerInfo):
        spec = getattr(w, "pending_create", None)
        if spec is not None and w.conn is not None:
            w.pending_create = None  # type: ignore[attr-defined]
            w.conn.push({"op": "create_actor_instance", "spec": spec})

    def _op_worker_online(self, conn, msg):
        """Worker is fully initialized: mark schedulable, deliver queued
        actor creation."""
        worker_hex = conn.meta.get("worker_hex")
        with self.lock:
            w = self.workers.get(worker_hex)
            if w is None:
                return
            if w.state == "dead":
                # Doomed while starting (e.g. its placement group was
                # removed before it registered): tell it to exit.
                try:
                    conn.push({"op": "exit"})
                except Exception:
                    pass
                return
            if w.kind == "pool" and w.state == "starting":
                w.state = "idle"
            self.deliver_pending_create(w)
        self._wake.set()
