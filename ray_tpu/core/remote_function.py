"""@remote task API (counterpart of python/ray/remote_function.py)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.core.runtime import func_content_id, get_runtime


class RemoteFunction:
    def __init__(self, fn, *, num_returns: int = 1,
                 num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 max_retries: int = 3,
                 runtime_env: Optional[dict] = None,
                 scheduling_strategy=None,
                 name: str = ""):
        if not (num_returns == "streaming"
                or (isinstance(num_returns, int) and num_returns >= 1)):
            raise ValueError(
                f"num_returns must be a positive int or 'streaming', "
                f"got {num_returns!r}")
        self._fn = fn
        self._num_returns = num_returns
        self._num_cpus = 1.0 if num_cpus is None else num_cpus
        self._num_tpus = num_tpus or 0.0
        self._resources = dict(resources or {})
        self._max_retries = max_retries
        self._runtime_env = runtime_env
        self._scheduling_strategy = scheduling_strategy
        self._name = name or getattr(fn, "__qualname__", "anonymous_task")
        self._blob: Optional[bytes] = None
        self._func_id: Optional[str] = None
        functools.update_wrapper(self, fn)

    def _resource_demand(self) -> Dict[str, float]:
        demand = dict(self._resources)
        if self._num_cpus:
            demand["CPU"] = self._num_cpus
        if self._num_tpus:
            demand["TPU"] = self._num_tpus
        return demand

    def _ensure_blob(self):
        if self._blob is None:
            self._blob = cloudpickle.dumps(self._fn)
            self._func_id = func_content_id(self._blob)
        return self._func_id, self._blob

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._name!r} cannot be called directly; "
            f"use {self._name}.remote(...)")

    def remote(self, *args, **kwargs):
        from ray_tpu.core.task_spec import KwargsMarker

        func_id, blob = self._ensure_blob()
        call_args = list(args)
        if kwargs:
            call_args.append(KwargsMarker(kwargs))
        # Opt-in tracing (util/tracing.py — reference tracing_helper wraps
        # _remote the same way): the submission span stays OPEN across
        # submit_task so the spec's trace_ctx names it as parent — the
        # worker-side execution span links to it, stitching the
        # driver→worker hop without extra wire traffic.
        from ray_tpu.util import tracing
        if tracing.is_tracing_enabled():
            attrs: Dict[str, Any] = {}
            with tracing.trace_span(f"submit:{self._name}", attrs):
                refs = self._submit(func_id, blob, call_args)
            attrs["object_ref"] = (refs.task_id.hex()
                                   if self._num_returns == "streaming"
                                   else refs[0].hex())
        else:
            refs = self._submit(func_id, blob, call_args)
        if self._num_returns == 1:
            return refs[0]
        return refs

    def _submit(self, func_id, blob, call_args):
        return get_runtime().submit_task(
            func_id, blob, call_args,
            num_returns=self._num_returns,
            resources=self._resource_demand(),
            max_retries=self._max_retries,
            name=self._name,
            runtime_env=self._runtime_env,
            scheduling_strategy=self._scheduling_strategy,
        )

    def bind(self, *args, **kwargs):
        """Author a DAG node for this task (reference function_node.py;
        see ray_tpu.dag)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def options(self, **overrides):
        """Return a copy with overridden submit options."""
        opts = {
            "num_returns": self._num_returns,
            "num_cpus": self._num_cpus,
            "num_tpus": self._num_tpus,
            "resources": self._resources,
            "max_retries": self._max_retries,
            "runtime_env": self._runtime_env,
            "scheduling_strategy": self._scheduling_strategy,
            "name": self._name,
        }
        opts.update(overrides)
        clone = RemoteFunction(self._fn, **opts)
        clone._blob = self._blob
        clone._func_id = self._func_id
        return clone
