"""Task / actor specifications shipped over the control plane.

Counterpart of the reference's TaskSpecification (src/ray/common/task/) and
the proto TaskSpec (src/ray/protobuf/common.proto): a compact picklable
record carrying identity, payload (function blob or cached function id),
arguments, resource demand and retry policy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID


@dataclass
class TaskArg:
    """One task argument: either an inline serialized value or an ObjectRef."""

    is_ref: bool
    # for refs:
    object_hex: str = ""
    # for inline values: raw serialized bytes (serialization.py layout)
    data: bytes = b""

    # Compact wire form: the control plane ships thousands of these per
    # second; tuple-reduce is ~5x faster than dataclass state pickling.
    def __reduce__(self):
        return (_mk_arg, (self.is_ref, self.object_hex, self.data))


def _mk_arg(is_ref, object_hex, data):
    a = TaskArg.__new__(TaskArg)
    a.is_ref = is_ref
    a.object_hex = object_hex
    a.data = data
    return a


@dataclass
class TaskSpec:
    task_id: TaskID
    func_id: str  # content hash of the function blob, for worker-side caching
    func_blob: Optional[bytes]  # cloudpickled callable; None if cached
    args: List[TaskArg]
    num_returns: int
    return_ids: List[ObjectID]
    resources: Dict[str, float]
    max_retries: int = 0
    retry_count: int = 0
    name: str = ""
    owner: str = ""  # worker hex that submitted
    # actor fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seq_no: int = -1
    # streaming-generator task (core/streaming.py): item objects are
    # derived from the task id instead of pre-registered return_ids
    is_streaming: bool = False
    # owner-direct actor task (runtime.py submit_actor_task): the result
    # is pushed straight back to the submitter over the direct actor
    # connection — the control server never sees the call (reference:
    # the direct actor transport + in-process store for small returns,
    # transport/direct_actor_task_submitter.cc)
    direct: bool = False
    # placement
    placement_group_hex: str = ""
    bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    runtime_env: Optional[Dict[str, Any]] = None
    # object hexes this task holds a reference on until it completes
    # (top-level ref args + refs captured inside inline args); the executor
    # decrefs them after the task finishes.
    borrows: List[str] = field(default_factory=list)
    # Compact trace propagation context (util/tracing.py make_trace_ctx):
    # (trace_id, parent span_id), or None when the submitter traces
    # nothing — the reference's _DictPropagator context riding TaskSpec
    # metadata, costing two short strings on the wire only when set.
    trace_ctx: Optional[Tuple[str, str]] = None

    # Hot-path wire form (submit/actor_task ride this thousands of times
    # per second): IDs travel as raw bytes, fields as a flat tuple.
    # ~5x faster than the default dataclass pickling on both ends.
    def __reduce__(self):
        return (_mk_spec, (
            self.task_id.binary() if self.task_id is not None else None,
            self.func_id, self.func_blob, self.args, self.num_returns,
            [o.binary() for o in self.return_ids], self.resources,
            self.max_retries, self.retry_count, self.name, self.owner,
            self.actor_id.binary() if self.actor_id is not None else None,
            self.method_name, self.seq_no, self.is_streaming,
            self.placement_group_hex, self.bundle_index,
            self.scheduling_strategy, self.runtime_env, self.borrows,
            self.direct, self.trace_ctx))


def _mk_spec(task_id, func_id, func_blob, args, num_returns, return_ids,
             resources, max_retries, retry_count, name, owner, actor_id,
             method_name, seq_no, is_streaming, placement_group_hex,
             bundle_index, scheduling_strategy, runtime_env, borrows,
             direct, trace_ctx=None):
    s = TaskSpec.__new__(TaskSpec)
    s.task_id = TaskID(task_id) if task_id is not None else None
    s.func_id = func_id
    s.func_blob = func_blob
    s.args = args
    s.num_returns = num_returns
    s.return_ids = [ObjectID(b) for b in return_ids]
    s.resources = resources
    s.max_retries = max_retries
    s.retry_count = retry_count
    s.name = name
    s.owner = owner
    s.actor_id = ActorID(actor_id) if actor_id is not None else None
    s.method_name = method_name
    s.seq_no = seq_no
    s.is_streaming = is_streaming
    s.placement_group_hex = placement_group_hex
    s.bundle_index = bundle_index
    s.scheduling_strategy = scheduling_strategy
    s.runtime_env = runtime_env
    s.borrows = borrows
    s.direct = direct
    s.trace_ctx = trace_ctx
    return s


class KwargsMarker:
    """Sentinel wrapper: kwargs dict shipped as the final positional arg.

    Lives here (not worker.py) because worker.py runs as ``__main__`` in
    worker processes — defining it there would create two distinct classes
    and break isinstance checks on deserialized markers.
    """

    __slots__ = ("kwargs",)

    def __init__(self, kwargs: dict):
        self.kwargs = kwargs


@dataclass
class ActorCreationSpec:
    actor_id: ActorID
    class_id: str
    class_blob: Optional[bytes]
    args: List[TaskArg]
    resources: Dict[str, float]
    max_restarts: int = 0
    # Per-method retry budget on actor RESTART (reference
    # max_task_retries): delivered-but-unfinished direct calls are
    # resubmitted by their owner when the actor comes back ALIVE.
    max_task_retries: int = 0
    name: str = ""
    namespace: str = ""
    max_concurrency: int = 1
    # Named concurrency groups {name: pool size}; methods route via
    # @ray_tpu.method(concurrency_group=...) (reference
    # concurrency_group_manager.cc per-group executor pools).
    concurrency_groups: Optional[Dict[str, int]] = None
    owner: str = ""
    placement_group_hex: str = ""
    bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    runtime_env: Optional[Dict[str, Any]] = None
    restart_count: int = 0
