"""Task / actor specifications shipped over the control plane.

Counterpart of the reference's TaskSpecification (src/ray/common/task/) and
the proto TaskSpec (src/ray/protobuf/common.proto): a compact picklable
record carrying identity, payload (function blob or cached function id),
arguments, resource demand and retry policy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID


@dataclass
class TaskArg:
    """One task argument: either an inline serialized value or an ObjectRef."""

    is_ref: bool
    # for refs:
    object_hex: str = ""
    # for inline values: raw serialized bytes (serialization.py layout)
    data: bytes = b""


@dataclass
class TaskSpec:
    task_id: TaskID
    func_id: str  # content hash of the function blob, for worker-side caching
    func_blob: Optional[bytes]  # cloudpickled callable; None if cached
    args: List[TaskArg]
    num_returns: int
    return_ids: List[ObjectID]
    resources: Dict[str, float]
    max_retries: int = 0
    retry_count: int = 0
    name: str = ""
    owner: str = ""  # worker hex that submitted
    # actor fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seq_no: int = -1
    # streaming-generator task (core/streaming.py): item objects are
    # derived from the task id instead of pre-registered return_ids
    is_streaming: bool = False
    # placement
    placement_group_hex: str = ""
    bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    runtime_env: Optional[Dict[str, Any]] = None
    # object hexes this task holds a reference on until it completes
    # (top-level ref args + refs captured inside inline args); the executor
    # decrefs them after the task finishes.
    borrows: List[str] = field(default_factory=list)


class KwargsMarker:
    """Sentinel wrapper: kwargs dict shipped as the final positional arg.

    Lives here (not worker.py) because worker.py runs as ``__main__`` in
    worker processes — defining it there would create two distinct classes
    and break isinstance checks on deserialized markers.
    """

    __slots__ = ("kwargs",)

    def __init__(self, kwargs: dict):
        self.kwargs = kwargs


@dataclass
class ActorCreationSpec:
    actor_id: ActorID
    class_id: str
    class_blob: Optional[bytes]
    args: List[TaskArg]
    resources: Dict[str, float]
    max_restarts: int = 0
    name: str = ""
    namespace: str = ""
    max_concurrency: int = 1
    owner: str = ""
    placement_group_hex: str = ""
    bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    runtime_env: Optional[Dict[str, Any]] = None
    restart_count: int = 0
