"""Shared-memory object store (plasma counterpart) + in-process memory store.

Reference counterparts:
  - plasma store embedded in the raylet (src/ray/object_manager/plasma/):
    here a directory of mmap-able segment files under /dev/shm, one per
    object, creatable by any worker process on the node and attachable
    zero-copy by any other.
  - CoreWorkerMemoryStore
    (src/ray/core_worker/store_provider/memory_store/memory_store.h): the
    in-process table of small/inline objects and pending futures.

TPU-native notes: segments are page-aligned flat buffers, so a deserialized
numpy array aliases shm and can be fed to jax.device_put without an extra
host copy (dlpack-style zero copy is the round-2 fast path).
"""

from __future__ import annotations

import mmap
import os
import shutil
import threading
from concurrent.futures import Future
from typing import Any, Dict, Optional

from ray_tpu.core.ids import ObjectID


def _default_capacity(shm_dir: str) -> int:
    """Arena size when unset: most of shm, sparse so it commits lazily."""
    try:
        free = shutil.disk_usage(shm_dir).free
    except OSError:
        free = 1 << 30
    return max(64 << 20, min(int(free * 0.8), 8 << 30))


class NativeSegment:
    """View over one object's payload inside the native arena."""

    __slots__ = ("name", "size", "_view", "writable")

    def __init__(self, name: str, size: int, view, writable: bool):
        self.name = name
        self.size = size
        self._view = view
        self.writable = writable

    @property
    def buf(self):
        return self._view

    def close(self):
        try:
            self._view.release()
        except (BufferError, AttributeError):
            pass


class ShmSegment:
    """One mmap'ed object segment; read or write view over a /dev/shm file."""

    __slots__ = ("name", "path", "size", "_mm", "_file", "writable")

    def __init__(self, name: str, path: str, size: int, mm, file, writable: bool):
        self.name = name
        self.path = path
        self.size = size
        self._mm = mm
        self._file = file
        self.writable = writable

    @property
    def buf(self) -> memoryview:
        return memoryview(self._mm)

    def close(self):
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass  # exported views still alive; mmap closes on GC
        try:
            self._file.close()
        except Exception:
            pass


class ShmObjectStore:
    """Node-local store of shared-memory segments, one file per object.

    The store itself is just a naming convention: segment files live at
    ``{shm_dir}/{prefix}-{object_hex}``; creation happens in whichever
    process produced the value, attachment in whichever consumes it.  The
    object *directory* (who has what, sizes, inline values) lives in the
    control store — this class only manages local segments.
    """

    def __init__(self, session_id: str, shm_dir: str = "/dev/shm",
                 capacity: int = 0):
        self.session_id = session_id
        self.shm_dir = shm_dir
        self._prefix = f"raytpu-{session_id}"
        self._lock = threading.Lock()
        self._open: Dict[str, object] = {}
        self._arena = None
        if os.environ.get("RAY_TPU_NATIVE_STORE", "1") != "0":
            try:
                from ray_tpu.native.store import NativeArena

                self._arena = NativeArena(
                    os.path.join(shm_dir, f"{self._prefix}-arena"),
                    capacity or _default_capacity(shm_dir), create=True)
            except Exception as e:
                # g++ missing etc. — fall back to file-per-object segments.
                # Loud, because a *partial* fallback (only some processes)
                # would split object visibility across the node.
                import logging

                logging.getLogger("ray_tpu").warning(
                    "native shm arena unavailable (%s); using "
                    "file-per-object store", e)
                self._arena = None

    @property
    def native(self) -> bool:
        return self._arena is not None

    def _path(self, object_id: ObjectID) -> str:
        return os.path.join(self.shm_dir, f"{self._prefix}-{object_id.hex()}")

    def create(self, object_id: ObjectID, size: int) -> ShmSegment:
        with self._lock:
            if self._arena is not None:
                from ray_tpu.native.store import (
                    ArenaFullError,
                    ObjectExistsError,
                )

                oid = object_id.binary()
                try:
                    try:
                        view = self._arena.create(oid, size)
                    except ObjectExistsError:
                        # task retry re-storing the same return id: replace
                        # (pinned old copies are orphaned by the C side)
                        self._arena.delete(oid)
                        view = self._arena.create(oid, size)
                    seg = NativeSegment(
                        object_id.hex(), size, view, writable=True)
                    self._open[object_id.hex()] = seg
                    return seg
                except ArenaFullError:
                    pass  # overflow: spill to a file-per-object segment
        path = self._path(object_id)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, max(size, 1))
            f = os.fdopen(fd, "r+b")
        except Exception:
            os.close(fd)
            os.unlink(path)
            raise
        mm = mmap.mmap(f.fileno(), max(size, 1))
        seg = ShmSegment(object_id.hex(), path, size, mm, f, writable=True)
        with self._lock:
            self._open[object_id.hex()] = seg
        return seg

    def seal(self, object_id: ObjectID):
        """Publish a written object (native arena; no-op for file-backed)."""
        with self._lock:
            if self._arena is not None:
                import errno

                from ray_tpu.native.store import ArenaError

                try:
                    self._arena.seal(object_id.binary())
                except ArenaError as e:
                    # ENOENT: an overflow object living in a file segment
                    if e.err != errno.ENOENT:
                        raise
                else:
                    # seal() drops the creator's arena pin, so the
                    # writable view cached by create() may alias a block
                    # that can now be deleted/reused (e.g. by spilling).
                    # Evict it; a later read re-attaches with a proper
                    # reader pin and a fresh view.
                    seg = self._open.pop(object_id.hex(), None)
                    if seg is not None:
                        seg.close()

    def attach(self, object_id: ObjectID, size: int) -> ShmSegment:
        key = object_id.hex()
        with self._lock:
            # cache check and pin happen under one lock hold so two racing
            # threads can't both pin (the loser's pin would never be
            # released)
            seg = self._open.get(key)
            if seg is not None:
                return seg
            if self._arena is not None:
                import errno

                from ray_tpu.native.store import ArenaError

                try:
                    view = self._arena.get(object_id.binary())
                except ArenaError as e:
                    if e.err != errno.EBUSY:
                        raise
                    # Pin-slot table full (many live reader processes):
                    # degrade to a copied read — correct, just not zero-copy.
                    data = self._arena.read_copy(object_id.binary())
                    view = memoryview(bytearray(data)) if data is not None \
                        else None
                    if view is not None:
                        seg = NativeSegment(key, len(view), view,
                                            writable=False)
                        self._open.setdefault(key, seg)
                        return seg
                if view is not None:
                    # The pin taken by get() is held for this process's
                    # lifetime: deserialized arrays may alias the buffer
                    # (pickle5 zero copy), mirroring how the file-backed
                    # path keeps the mmap open.
                    seg = NativeSegment(key, len(view), view, writable=False)
                    self._open.setdefault(key, seg)
                    return seg
                # else: overflow object — fall through to the file path
        path = self._path(object_id)
        f = open(path, "rb")
        mm = mmap.mmap(f.fileno(), max(size, 1), prot=mmap.PROT_READ)
        seg = ShmSegment(key, path, size, mm, f, writable=False)
        with self._lock:
            self._open.setdefault(key, seg)
        return seg

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            if self._arena is not None and \
                    self._arena.contains(object_id.binary()):
                return True
        return os.path.exists(self._path(object_id))

    def release(self, object_id: ObjectID):
        """Close the local mapping (does not delete the object)."""
        with self._lock:
            seg = self._open.pop(object_id.hex(), None)
            if seg is not None:
                seg.close()
                if self._arena is not None and not seg.writable:
                    self._arena.release(object_id.binary())

    def delete(self, object_id: ObjectID):
        with self._lock:
            seg = self._open.pop(object_id.hex(), None)
            if seg is not None:
                seg.close()
            if self._arena is not None:
                self._arena.delete(object_id.binary())
        try:
            os.unlink(self._path(object_id))
        except FileNotFoundError:
            pass

    def sweep(self, alive_pids) -> int:
        """Drop pins held by dead processes (node-daemon duty; native only)."""
        with self._lock:
            if self._arena is not None:
                return self._arena.sweep(list(alive_pids))
        return 0

    def stats(self):
        """(capacity, used, num_objects, evicted_bytes) — native arena only."""
        with self._lock:
            if self._arena is not None:
                return self._arena.stats()
        return (0, 0, 0, 0)

    def cleanup(self):
        with self._lock:
            segs = list(self._open.values())
            self._open.clear()
            arena, self._arena = self._arena, None
        for seg in segs:
            seg.close()
        if arena is not None:
            try:
                arena.close()
            except Exception:
                pass
        # best-effort sweep of this session's files
        try:
            for name in os.listdir(self.shm_dir):
                if name.startswith(self._prefix):
                    try:
                        os.unlink(os.path.join(self.shm_dir, name))
                    except OSError:
                        pass
        except OSError:
            pass


class InProcessStore:
    """Per-process table of resolved values and pending futures."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[ObjectID, Any] = {}
        self._futures: Dict[ObjectID, list] = {}

    def put(self, object_id: ObjectID, value: Any):
        with self._lock:
            self._values[object_id] = value
            waiters = self._futures.pop(object_id, [])
        for fut in waiters:
            if not fut.done():
                fut.set_result(value)

    def get_future(self, object_id: ObjectID) -> Future:
        fut: Future = Future()
        with self._lock:
            if object_id in self._values:
                fut.set_result(self._values[object_id])
                return fut
            self._futures.setdefault(object_id, []).append(fut)
        return fut

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._values

    def peek(self, object_id: ObjectID):
        with self._lock:
            return self._values.get(object_id)

    def pop(self, object_id: ObjectID):
        with self._lock:
            self._values.pop(object_id, None)
            self._futures.pop(object_id, None)

    def fail(self, object_id: ObjectID, exc: BaseException):
        with self._lock:
            self._values[object_id] = exc
            waiters = self._futures.pop(object_id, [])
        for fut in waiters:
            if not fut.done():
                fut.set_result(exc)
