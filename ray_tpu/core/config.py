"""Runtime configuration flags.

Counterpart of the reference's RAY_CONFIG flag system
(src/ray/common/ray_config_def.h): every knob has a typed default and can be
overridden by an ``RAY_TPU_<NAME>`` environment variable or via
``init(_system_config={...})``.  Kept deliberately small; grow as subsystems
land.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields


def _env_override(name: str, default):
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    t = type(default)
    if t is bool:
        return raw.lower() in ("1", "true", "yes")
    return t(raw)


@dataclass
class Config:
    # -- object store ---------------------------------------------------
    # Objects at or below this size are stored inline in the object
    # directory instead of a shared-memory segment (reference:
    # max_direct_call_object_size, ray_config_def.h).
    max_inline_object_size: int = 100 * 1024
    # Owner-direct actor results at or below this size ride the direct
    # actor connection back to the submitter (runtime.py); larger ones
    # fall back to the shared-memory store via the head.
    max_direct_result_bytes: int = 1024 * 1024
    # Shared-memory store capacity (bytes). 0 = unlimited (bounded by /dev/shm).
    object_store_memory: int = 0
    # Directory backing the shared-memory store.
    shm_dir: str = "/dev/shm"
    # Spill shm objects to external storage once the arena passes this
    # usage fraction (reference: object_spilling_threshold). 0 disables.
    object_spilling_threshold: float = 0.8
    # External storage spec: '' = <session_dir>/spilled, a path, or a
    # smart_open URI prefix (s3://...). See core/external_storage.py.
    spill_storage: str = ""
    # Objects younger than this are not spilled (bounds the window where
    # a client could hold a stale in-shm location).
    spill_min_age_s: float = 1.0

    # -- lineage reconstruction -----------------------------------------
    # Re-execute the producing task when an object's only copy is lost
    # (reference: enable_object_reconstruction flag ray_config_def.h,
    # ObjectRecoveryManager object_recovery_manager.h, lineage
    # resubmission task_manager.h:208).
    enable_object_reconstruction: bool = True
    # Per-object cap on reconstruction re-executions (reference:
    # task_retries consumed by reconstruction).
    object_reconstruction_max_attempts: int = 3
    # Cap on retained task records + lineage links; oldest finished
    # records are evicted past this (reference: bounded lineage
    # max_lineage_bytes + RAY_task_events_max_num_task_in_gcs).
    max_lineage_entries: int = 100_000

    # -- memory monitor (reference memory_monitor.h + OOM killer) -------
    # Kill-and-retry the newest retriable task when host memory usage
    # crosses this fraction. 0 disables the monitor.
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_s: float = 1.0
    # Minimum seconds between OOM kills, so reclaim from one kill lands
    # before the next is considered (prevents cascade-killing the pool).
    oom_kill_cooldown_s: float = 10.0
    # Above this usage, non-retriable tasks become eligible too (last
    # resort before the kernel OOM-kills the node).
    memory_usage_threshold_critical: float = 0.98

    # -- scheduling -----------------------------------------------------
    # Max worker processes started eagerly at init.
    prestart_workers: int = 0
    # Hard cap on worker processes per node.
    max_workers_per_node: int = 64
    # Seconds a leased idle worker is kept before being returned to pool.
    worker_lease_timeout_s: float = 0.0
    # Top-k random choice among feasible nodes (reference hybrid policy
    # scheduling/policy/hybrid_scheduling_policy.h).
    scheduler_top_k_fraction: float = 0.2
    # Owner-direct task leases (reference: the lease protocol of
    # CoreWorkerDirectTaskSubmitter, direct_task_transport.h:75/:353 —
    # the owner leases workers from the scheduler once, then pushes
    # task specs peer-to-peer and reuses the lease while same-shaped
    # work remains).  Off = every task transits the head.
    direct_task_leases: bool = True
    # In-flight pipeline depth per leased worker (reference pipelines
    # via max_tasks_in_flight_per_worker).
    lease_pipeline_depth: int = 4
    # Owner returns an idle lease after this long without queued work.
    lease_idle_timeout_s: float = 0.25
    # Cap on workers one lease request asks for.
    max_lease_workers_per_request: int = 16
    # How long an unanswered lease ask holds pipeline depth at 1 (so
    # early tasks spread across incoming workers).  Past this, the ask
    # is treated as queued-for-capacity and full-depth pipelining
    # resumes on the workers already held.
    lease_scaleup_clamp_s: float = 1.0

    # -- fault tolerance ------------------------------------------------
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0

    # -- rpc ------------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    rpc_max_message_bytes: int = 512 * 1024 * 1024
    # Address this host's rpc servers BIND. 127.0.0.1 keeps single-host
    # setups private; set to the host's reachable IP or 0.0.0.0 for real
    # multi-host clusters.
    node_ip_address: str = "127.0.0.1"
    # Address ADVERTISED to peers (actor transport, node object plane).
    # '' = node_ip_address, except 0.0.0.0/:: resolves to the hostname's
    # IP (an advertised wildcard would point peers at themselves).
    node_advertise_ip: str = ""

    def advertised_host(self) -> str:
        host = self.node_advertise_ip or self.node_ip_address
        if host in ("0.0.0.0", "::"):
            import socket

            try:
                host = socket.gethostbyname(socket.gethostname())
            except OSError:
                host = "127.0.0.1"
        return host
    # Chunk size for cross-node object pulls (reference
    # object_manager_default_chunk_size, ray_config_def.h).
    transfer_chunk_bytes: int = 8 * 1024 * 1024
    # In-flight fetch_chunk requests per object pull (reference
    # object_manager_max_bytes_in_flight, as a chunk-count window).
    # 1 restores the legacy one-chunk-at-a-time ping-pong.
    pull_window: int = 4
    # A spawned worker that hasn't registered within this window is
    # presumed dead (its node crashed mid-spawn) and its work is retried.
    worker_register_timeout_s: float = 60.0

    # -- control-plane persistence (reference: GCS StoreClient / Redis) --
    # Path for the control server's KV journal; '' = in-memory only.
    # With a path set, the cluster KV (user KV, runtime-env packages,
    # named-function registrations AND their blobs) PLUS cluster
    # metadata (session id, named actors, placement groups, logical
    # nodes) survive a head restart.
    gcs_store_path: str = ""
    # Fixed control-server port (0 = ephemeral). A restartable head
    # needs a stable port so workers/drivers/nodes can redial it.
    control_port: int = 0
    # How long clients (workers, drivers, node managers) retry redialing
    # a lost head before giving up (reference: raylet reconnect backoff
    # after NotifyGCSRestart, node_manager.proto:383). 0 disables
    # reconnection (a lost head kills the client, the old behavior).
    gcs_reconnect_timeout_s: float = 30.0
    # After a head restart, how long a restored-but-unclaimed entity
    # (RESTARTING actor nobody re-announced, re-subscribed object whose
    # producer never reported) waits before being failed/respawned.
    head_restart_grace_s: float = 15.0

    # -- logging --------------------------------------------------------
    log_dir: str = ""

    def __post_init__(self):
        for f in fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    def apply_overrides(self, overrides: dict | None):
        if not overrides:
            return self
        valid = {f.name for f in fields(self)}
        for k, v in overrides.items():
            if k not in valid:
                raise ValueError(f"Unknown system config key: {k}")
            setattr(self, k, v)
        return self


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
    return _global_config


def reset_config():
    global _global_config
    _global_config = None
