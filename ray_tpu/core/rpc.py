"""Minimal TCP RPC: length-prefixed pickled messages, threaded server.

Counterpart of the reference's gRPC substrate (src/ray/rpc/).  grpcio is not
available in this environment, so the control plane speaks a tiny framed
protocol over TCP sockets:

    [1-byte kind][8-byte request id][4-byte len][pickle payload]

kind: 0 = request (expects response), 1 = response, 2 = one-way,
      3 = JSON request (payload is UTF-8 JSON; response is JSON too),
      5 = batch (payload is one pickle of [(kind, req_id, payload), ...]),
      6 = JSON batch (payload is a JSON array of [kind, req_id, msg]),
      7 = zero-copy envelope (pickle5 stream + out-of-band buffers,
          scatter-gathered onto the socket; see KIND_OOB below).

Kind 3 is the cross-language door (reference: the gRPC protos any
language can speak): non-Python frontends (cpp/ client) call the same
ops with JSON payloads and get `{"status": "ok"|"err", ...}` JSON back;
bytes values are transported as {"__bytes_b64__": ...}.

Kind 5/6 are the control-plane coalescing frames (reference: Ray's
batched worker↔raylet traffic): senders buffer while a write is on the
wire and flush whatever accumulated as ONE frame, so a burst of small
control messages costs a handful of sendalls instead of thousands.  The
receiver unpacks and dispatches sub-messages in order; semantics are
identical to having received each sub-frame individually.  Batches are
never nested, and the server only emits pickle batches to peers that
have themselves spoken pickle — JSON-only peers (the C++ client) keep
getting plain frames.  Set RAY_TPU_RPC_NO_BATCH=1 to disable coalescing
entirely and restore the one-frame-per-message protocol byte for byte.

Server: thread per connection, handler invoked per message; handler may
return a value (sent back as response) or None for one-way messages.
Clients are thread-safe; concurrent calls are matched by request id.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ray_tpu.core.log_once import warn_once

logger = logging.getLogger(__name__)

_FRAME = struct.Struct("<BQI")

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_ONEWAY = 2
KIND_REQUEST_JSON = 3
# One-way server→client push encoded as JSON — for non-Python peers
# (the C++ worker's task delivery; cpp/include/ray_tpu/worker.h).
KIND_ONEWAY_JSON = 4
# Coalesced frame: payload pickles a list of (kind, req_id, payload)
# sub-frames, dispatched in order on the receiving side.
KIND_BATCH = 5
# Cross-language form: payload is a JSON array of [kind, req_id, msg]
# triples (kind 3 entries only; each gets its own KIND_RESPONSE).
KIND_BATCH_JSON = 6
# Zero-copy envelope: payload is [<B inner_kind><I pkl_len><I nbufs>
# <Q buf_len>*nbufs][pickle5 stream][buf0][buf1]... — large buffers
# (numpy arrays, inline object bytes) ride OUT-OF-BAND after the pickle
# stream and are scatter-gathered onto the socket with sendmsg, so a
# 64 MiB arg is never memcpy'd through the wire encoder.  Pickle-
# speaking peers only (the JSON path never emits it).
KIND_OOB = 7

_OOB_INDEX = struct.Struct("<BII")

_TRUTHY = ("1", "true", "yes", "on")


_ZC_MIN: int | None = None


def _zerocopy_min() -> int:
    """Payload size from which frames switch to scatter-gather writes
    and pickle5 buffers go out-of-band.  <= 0 disables the path
    (RAY_TPU_ZEROCOPY_MIN_BYTES; cached after first read)."""
    global _ZC_MIN
    v = _ZC_MIN
    if v is None:
        try:
            v = int(os.environ.get(
                "RAY_TPU_ZEROCOPY_MIN_BYTES", str(512 << 10)))
        except ValueError:
            v = 512 << 10
        if v <= 0:
            v = 1 << 62
        _ZC_MIN = v
    return v


def _sendmsg_all(sock: socket.socket, parts) -> None:
    """Write a scatter-gather list fully, advancing views on partial
    sends.  Equivalent to sendall(b"".join(parts)) without building the
    joined copy."""
    views = [memoryview(p).cast("B") for p in parts if len(p)]
    while views:
        n = sock.sendmsg(views)
        while n > 0 and views:
            head = views[0]
            if n >= len(head):
                n -= len(head)
                views.pop(0)
            else:
                views[0] = head[n:]
                n = 0


def _part_len(payload) -> int:
    """Wire length of a payload that is either bytes or a tuple of
    scatter-gather parts (KIND_OOB)."""
    if isinstance(payload, tuple):
        return sum(len(p) for p in payload)
    return len(payload)


def _wrap_big_bytes(msg, zc: int):
    """Shallow rewrite of a message dict: top-level bytes values (and
    bytes values one level down inside list-of-dict batches) at or over
    the zero-copy threshold are wrapped in PickleBuffer so the protocol-5
    encoder hands them to the buffer callback instead of copying them
    into the pickle stream.  Returns msg unchanged when nothing is big."""
    if not isinstance(msg, dict):
        return msg
    out = None
    for k, v in msg.items():
        if isinstance(v, (bytes, bytearray)) and len(v) >= zc:
            if out is None:
                out = dict(msg)
            out[k] = pickle.PickleBuffer(v)
        elif isinstance(v, list) and v and isinstance(v[0], dict):
            new_list = None
            for i, item in enumerate(v):
                if not isinstance(item, dict):
                    continue
                rew = _wrap_big_bytes(item, zc)
                if rew is not item:
                    if new_list is None:
                        new_list = list(v)
                    new_list[i] = rew
            if new_list is not None:
                if out is None:
                    out = dict(msg)
                out[k] = new_list
    return msg if out is None else out


def _encode_payload(msg) -> tuple[int | None, "bytes | tuple"]:
    """Encode a message for the wire.  Returns (None, pickle_bytes)
    for ordinary messages, or (KIND_OOB, parts_tuple) when at least one
    buffer crossed the zero-copy threshold — the parts are
    (index, pickle_stream, buf0, ...) and the caller's frame kind is
    folded into the index as inner_kind at send time."""
    zc = _zerocopy_min()
    bufs: list[memoryview] = []

    def _cb(pb):
        raw = pb.raw()
        if raw.nbytes >= zc:
            bufs.append(raw.cast("B"))
            return False  # take out-of-band
        return True  # small buffers stay in the pickle stream

    pkl = pickle.dumps(_wrap_big_bytes(msg, zc), protocol=5,
                       buffer_callback=_cb)
    if not bufs:
        return None, pkl
    WIRE.on_zerocopy(sum(b.nbytes for b in bufs))
    return KIND_OOB, (pkl, *bufs)


def _oob_parts(inner_kind: int, parts: tuple) -> tuple:
    """Prefix the (pickle, bufs...) parts with the KIND_OOB index."""
    pkl = parts[0]
    bufs = parts[1:]
    index = _OOB_INDEX.pack(inner_kind, len(pkl), len(bufs))
    if bufs:
        index += struct.pack("<%dQ" % len(bufs),
                             *(len(b) for b in bufs))
    return (index, *parts)


def _decode_oob(payload) -> tuple[int, Any]:
    """Inverse of _encode_payload/_oob_parts: returns
    (inner_kind, message).  Out-of-band buffers are materialized as
    bytes sliced straight from the received payload (one copy, same as
    the in-band path) so downstream consumers keep bytes semantics."""
    mv = memoryview(payload)
    inner_kind, pkl_len, nbufs = _OOB_INDEX.unpack_from(mv, 0)
    off = _OOB_INDEX.size
    lens = ()
    if nbufs:
        lens = struct.unpack_from("<%dQ" % nbufs, mv, off)
        off += 8 * nbufs
    pkl = mv[off:off + pkl_len]
    off += pkl_len
    bufs = []
    for n in lens:
        bufs.append(bytes(mv[off:off + n]))
        off += n
    return inner_kind, pickle.loads(pkl, buffers=bufs)


def batching_enabled() -> bool:
    """Master switch for wire-level coalescing.  Checked at Client /
    Connection construction (not per send) so a process-wide
    RAY_TPU_RPC_NO_BATCH=1 restores the legacy protocol exactly."""
    return os.environ.get(
        "RAY_TPU_RPC_NO_BATCH", "").strip().lower() not in _TRUTHY


def _batch_caps() -> tuple[int, int]:
    """(max messages, max payload bytes) folded into one KIND_BATCH
    frame.  Oversized runs split into several frames within one drain
    round; a single message larger than the byte cap still goes out
    (as a plain frame) — the cap bounds coalescing, not message size."""
    try:
        msgs = int(os.environ.get("RAY_TPU_RPC_BATCH_MAX_MSGS", "512"))
    except ValueError:
        msgs = 512
    try:
        nbytes = int(os.environ.get(
            "RAY_TPU_RPC_BATCH_MAX_BYTES", str(4 << 20)))
    except ValueError:
        nbytes = 4 << 20
    return max(2, msgs), max(1 << 16, nbytes)


def _flush_us() -> int:
    """Microseconds the coalescing sender lingers before each flush.
    0 (default) keeps the first message on an idle link immediate;
    >0 trades that first-message latency for fuller batches when the
    traffic is a ping-pong request/ack chain whose turns would
    otherwise each ride their own frame."""
    try:
        return max(0, int(os.environ.get("RAY_TPU_RPC_FLUSH_US", "0")))
    except ValueError:
        return 0


def _to_jsonable(value: Any):
    if isinstance(value, (bytes, bytearray, memoryview)):
        return {"__bytes_b64__":
                base64.b64encode(bytes(value)).decode("ascii")}
    if isinstance(value, dict):
        return {str(k): _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    return value


def _from_jsonable(value: Any):
    if isinstance(value, dict):
        if set(value) == {"__bytes_b64__"}:
            return base64.b64decode(value["__bytes_b64__"])
        return {k: _from_jsonable(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_from_jsonable(v) for v in value]
    return value


class RpcError(ConnectionError):
    pass


def pull_window() -> int:
    """In-flight fetch_chunk requests per pull (RAY_TPU_PULL_WINDOW,
    default 4).  1 restores the legacy one-chunk-at-a-time ping-pong
    byte for byte."""
    try:
        w = int(os.environ.get("RAY_TPU_PULL_WINDOW", "4"))
    except ValueError:
        w = 4
    return max(1, w)


def pull_object_chunked(client: "Client", obj_hex: str, size: int,
                        chunk: int, timeout: float = 60.0, *,
                        window: Optional[int] = None,
                        into=None) -> Optional[bytes]:
    """Pull an object's bytes via fetch_chunk requests (the cross-node
    object plane's one wire loop — shared by workers pulling from peer
    nodes and the head proxying for thin clients).

    Keeps up to `window` requests in flight, multiplexed on the
    client's request ids (reference ObjectManager chunked pull,
    object_buffer_pool.h): the peer serves chunk k+1 while chunk k is
    still on the wire, so the transfer runs at pipeline speed instead
    of one round trip per chunk.  Chunks land at fixed offsets, so
    out-of-window completion order never matters.  `into` (a writable
    buffer of at least `size` bytes — typically a pre-created arena
    segment) receives chunks directly as they arrive, skipping the
    full-size intermediate copy; the return value is then None.
    Raises on a short, oversized, or failed read."""
    chunk = max(1 << 20, chunk)
    if window is None:
        window = pull_window()
    window = max(1, int(window))
    dest = bytearray(size) if into is None else into
    inflight: deque = deque()  # (offset, length, pending call)
    next_off = 0
    try:
        while inflight or next_off < size:
            while next_off < size and len(inflight) < window:
                n = min(chunk, size - next_off)
                pending = client.call_async(
                    {"op": "fetch_chunk", "obj": obj_hex, "size": size,
                     "offset": next_off, "length": n})
                inflight.append((next_off, n, pending))
                next_off += n
            off, n, pending = inflight.popleft()
            part = pending.result(timeout=timeout)
            if not part:
                raise RpcError(f"peer no longer serves object {obj_hex}")
            if len(part) != n:
                # Offsets are fixed up front, so a short reply cannot be
                # re-requested mid-window; an oversized one must not
                # silently grow past the declared object size.  Both
                # mean the peer's copy is not the directory's object.
                raise RpcError(
                    f"peer returned {len(part)} bytes for a {n}-byte "
                    f"chunk of object {obj_hex}")
            dest[off:off + n] = part
    except BaseException:
        # Abandon outstanding requests: late responses for popped ids
        # are dropped by the recv loop instead of leaking table entries.
        for _, _, pending in inflight:
            pending.discard()
        raise
    return None if into is not None else bytes(dest)


class _RemoteTraceback(Exception):
    pass


def _send_frame(sock: socket.socket, kind: int, req_id: int, payload):
    """payload: bytes, or a tuple of scatter-gather parts (KIND_OOB /
    any frame whose payload crossed the zero-copy threshold).  Large
    payloads go out via sendmsg so the header+payload join — a full
    copy of the payload — never happens."""
    n = _part_len(payload)
    header = _FRAME.pack(kind, req_id, n)
    if isinstance(payload, tuple):
        _sendmsg_all(sock, (header, *payload))
    elif n >= _zerocopy_min():
        WIRE.on_zerocopy(n)
        _sendmsg_all(sock, (header, payload))
    else:
        sock.sendall(header + payload)
    WIRE.on_frame_sent(kind, len(header) + len(payload))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 4 << 20))
        if not chunk:
            raise RpcError("connection closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket):
    header = _recv_exact(sock, _FRAME.size)
    kind, req_id, length = _FRAME.unpack(header)
    payload = _recv_exact(sock, length) if length else b""
    return kind, req_id, payload


_KIND_NAMES = {
    KIND_REQUEST: "request", KIND_RESPONSE: "response",
    KIND_ONEWAY: "oneway", KIND_REQUEST_JSON: "request_json",
    KIND_ONEWAY_JSON: "oneway_json", KIND_BATCH: "batch",
    KIND_BATCH_JSON: "batch_json",
}

_flight = None  # lazily imported flight recorder module (or False)


def _flight_recorder():
    global _flight
    if _flight is None:
        try:
            from ray_tpu.util import flight_recorder as fr

            _flight = fr
        except Exception:
            _flight = False
    return _flight


class _WireStats:
    """Process-wide wire telemetry, one lock update per FRAME (not per
    message): frames/messages/batches/bytes in both directions, per-kind
    sent counts, and a batch-size histogram whose le="1" bucket is the
    plain-frame count — coalesced-vs-plain ratio falls out of the same
    series.  Frames are syscall-bounded, so the lock is off the per-
    message hot path; exported through util/metrics.py via
    wire_metric_snapshots()."""

    BATCH_BOUNDS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                    256.0, 512.0)

    def __init__(self):
        self.lock = threading.Lock()
        self.frames_sent = 0
        self.msgs_sent = 0
        self.batches_sent = 0
        self.bytes_sent = 0
        self.frames_received = 0
        self.msgs_received = 0
        self.batches_received = 0
        self.bytes_received = 0
        self.sent_by_kind: dict[int, int] = {}
        self.batch_buckets = [0] * (len(self.BATCH_BOUNDS) + 1)
        self.batch_sum = 0.0
        self.batch_count = 0
        self.zerocopy_bytes = 0

    def _observe_size_locked(self, nmsgs: int):
        for i, b in enumerate(self.BATCH_BOUNDS):
            if nmsgs <= b:
                self.batch_buckets[i] += 1
                break
        else:
            self.batch_buckets[-1] += 1
        self.batch_sum += nmsgs
        self.batch_count += 1

    def on_frame_sent(self, kind: int, nbytes: int, nmsgs: int = 1):
        with self.lock:
            self.frames_sent += 1
            self.msgs_sent += nmsgs
            self.bytes_sent += nbytes
            self.sent_by_kind[kind] = self.sent_by_kind.get(kind, 0) + 1
            if nmsgs > 1:
                self.batches_sent += 1
            self._observe_size_locked(nmsgs)

    def on_frames_sent(self, entries):
        """Coalescing-sender drain round: one lock acquisition for the
        whole round's (kind, nmsgs, nbytes) frames."""
        with self.lock:
            for kind, nmsgs, nbytes in entries:
                self.frames_sent += 1
                self.msgs_sent += nmsgs
                self.bytes_sent += nbytes
                self.sent_by_kind[kind] = \
                    self.sent_by_kind.get(kind, 0) + 1
                if nmsgs > 1:
                    self.batches_sent += 1
                self._observe_size_locked(nmsgs)
        fr = _flight_recorder()
        if fr:
            for kind, nmsgs, nbytes in entries:
                if nmsgs > 1:
                    fr.record("wire", "batch_flush", msgs=nmsgs,
                              bytes=nbytes)

    def on_zerocopy(self, nbytes: int):
        """Payload bytes that reached the socket via scatter-gather
        (sendmsg) instead of being memcpy'd through the encoder."""
        with self.lock:
            self.zerocopy_bytes += nbytes

    def on_frame_received(self, kind: int, nbytes: int, nmsgs: int = 1):
        with self.lock:
            self.frames_received += 1
            self.msgs_received += nmsgs
            self.bytes_received += nbytes
            if kind in (KIND_BATCH, KIND_BATCH_JSON):
                self.batches_received += 1


WIRE = _WireStats()


def wire_metric_snapshots() -> list:
    """This process's wire counters as metric-snapshot dicts in the
    util/metrics.py exposition shape — merged into local_snapshots() so
    they publish/aggregate through the standard __metrics__/ KV path
    without rpc.py depending on the metrics registry."""
    w = WIRE
    with w.lock:
        directions = {
            "rpc_frames_total": (w.frames_sent, w.frames_received),
            "rpc_msgs_total": (w.msgs_sent, w.msgs_received),
            "rpc_batches_total": (w.batches_sent, w.batches_received),
            "rpc_bytes_total": (w.bytes_sent, w.bytes_received),
        }
        by_kind = dict(w.sent_by_kind)
        hist = [list(w.batch_buckets), w.batch_sum, w.batch_count]
        zc_bytes = w.zerocopy_bytes
    descs = {
        "rpc_frames_total": "Control-plane frames on the wire",
        "rpc_msgs_total": "Control-plane messages (batch entries count "
                          "individually)",
        "rpc_batches_total": "Coalesced KIND_BATCH frames",
        "rpc_bytes_total": "Control-plane payload bytes (incl. headers)",
    }
    snaps = []
    for name, (sent, received) in directions.items():
        snaps.append({
            "name": name, "kind": "counter", "description": descs[name],
            "series": {(("direction", "sent"),): float(sent),
                       (("direction", "received"),): float(received)},
        })
    kind_series = {
        (("direction", "sent"), ("kind", _KIND_NAMES.get(k, str(k)))):
            float(v)
        for k, v in by_kind.items() if v}
    if kind_series:
        snaps.append({
            "name": "rpc_frames_by_kind_total", "kind": "counter",
            "description": "Sent frames by wire kind",
            "series": kind_series,
        })
    snaps.append({
        "name": "ray_tpu_zerocopy_bytes_total", "kind": "counter",
        "description": "Payload bytes sent out-of-band via scatter-"
                       "gather (never copied through the wire encoder)",
        "series": {(): float(zc_bytes)},
    })
    snaps.append({
        "name": "rpc_batch_size", "kind": "histogram",
        "description": "Messages per sent frame (le=1 bucket = plain "
                       "frames; higher = coalesced)",
        "boundaries": list(_WireStats.BATCH_BOUNDS),
        "series": {(): hist},
    })
    return snaps


class _CoalescingSender:
    """Adaptive per-connection send coalescer — Nagle without the
    latency cliff.  The first message on an idle link is flushed
    IMMEDIATELY on the enqueuing thread (no timer, no added latency);
    messages arriving while that write is still on the wire pile into
    the buffer, and the draining thread flushes whatever accumulated as
    ONE KIND_BATCH frame when the in-flight sendall returns.  An
    uncontended link therefore produces byte-for-byte the unbatched
    protocol (single-entry rounds keep the plain frame encoding), while
    contended links amortize framing, syscalls, and lock handoffs.

    Payloads are pre-encoded by the caller, so per-entry size is known
    here and the receiver's sub-dispatch is identical to the plain
    path.  One instance guards one socket; `wire_lock` is the owner's
    existing socket write lock (JSON responses and legacy paths still
    write under it directly, so batched and direct frames never
    interleave mid-frame)."""

    def __init__(self, sock: socket.socket, wire_lock: threading.Lock):
        self._sock = sock
        self._wire_lock = wire_lock
        # RLock: appending can allocate → GC → __del__ hooks; a re-
        # entrant enqueue from the same thread must not deadlock.
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._buf: list[tuple[int, int, bytes]] = []
        self._sending = False
        self.max_msgs, self.max_bytes = _batch_caps()
        self.linger_s = _flush_us() / 1e6
        # Telemetry for tests and the RPC microbench probe.
        self.frames_sent = 0
        self.msgs_sent = 0
        self.batches_sent = 0

    def send(self, kind: int, req_id: int, payload: bytes,
             wait: bool = False):
        """Enqueue one message.  If no write is in flight the calling
        thread becomes the drainer (immediate flush); otherwise the
        message rides the next coalesced frame.  wait=True blocks until
        the message is on the socket — backpressure-sensitive paths
        (object-plane chunk streaming) opt in to keep their in-flight
        byte budget honest."""
        with self._lock:
            self._buf.append((kind, req_id, payload))
            self.msgs_sent += 1
            if self._sending:
                if wait:
                    while self._buf or self._sending:
                        self._cv.wait()
                return
            self._sending = True
        self._drain()

    def flush(self):
        """Block until every message enqueued before this call is on
        the socket.  Ordering fences (worker oversized-result handoff,
        shutdown) need the hard guarantee; on an idle link this returns
        immediately."""
        while True:
            with self._lock:
                if self._sending:
                    self._cv.wait()
                    continue
                if not self._buf:
                    return
                self._sending = True
            self._drain(linger=False)

    def _drain(self, linger: bool = True):
        """Flush loop run by whichever thread claimed `_sending`: swap
        the buffer out, encode, write, repeat until nothing new arrived
        during the write.  With RAY_TPU_RPC_FLUSH_US > 0 each round
        lingers that long before swapping so trailing messages from
        ping-pong peers ride the same frame; flush() fences skip the
        linger (linger=False) — a fence wants the bytes out now."""
        try:
            while True:
                with self._lock:
                    if not self._buf:
                        self._sending = False
                        self._cv.notify_all()
                        return
                    if linger and self.linger_s > 0.0:
                        # cv.wait drops the lock so enqueuers can pile
                        # into the buffer during the linger window.
                        self._cv.wait(timeout=self.linger_s)
                    batch, self._buf = self._buf, []
                for frame in self._encode(batch):
                    with self._wire_lock:
                        if isinstance(frame, tuple):
                            _sendmsg_all(self._sock, frame)
                        else:
                            self._sock.sendall(frame)
        except BaseException:
            with self._lock:
                self._sending = False
                self._cv.notify_all()
            raise

    def _encode(self, batch: list) -> list:
        frames = []  # bytes, or tuple of scatter-gather parts
        stats = []  # (kind, nmsgs, frame bytes) per frame, for WIRE
        i, n = 0, len(batch)
        while i < n:
            # Greedy size/count-capped run starting at i.  Multi-part
            # (KIND_OOB) payloads can't ride a pickled KIND_BATCH —
            # they always form solo frames, and break runs.
            run_bytes = _part_len(batch[i][2])
            j = i + 1
            if not isinstance(batch[i][2], tuple):
                while (j < n and j - i < self.max_msgs
                       and not isinstance(batch[j][2], tuple)
                       and run_bytes + len(batch[j][2])
                       <= self.max_bytes):
                    run_bytes += len(batch[j][2])
                    j += 1
            if j - i == 1:
                kind, req_id, payload = batch[i]
                plen = _part_len(payload)
                header = _FRAME.pack(kind, req_id, plen)
                if isinstance(payload, tuple):
                    frames.append((header, *payload))
                elif plen >= _zerocopy_min():
                    WIRE.on_zerocopy(plen)
                    frames.append((header, payload))
                else:
                    frames.append(header + payload)
                stats.append((kind, 1, _FRAME.size + plen))
            else:
                blob = pickle.dumps(batch[i:j], protocol=5)
                frames.append(_FRAME.pack(KIND_BATCH, 0, len(blob)) + blob)
                self.batches_sent += 1
                stats.append((KIND_BATCH, j - i, len(frames[-1])))
            self.frames_sent += 1
            i = j
        WIRE.on_frames_sent(stats)
        return frames


class Connection:
    """Server-side handle to a connected peer; supports pushing messages."""

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        self.send_lock = threading.Lock()
        self.meta: dict = {}
        self.alive = True
        # Flips True the first time the peer sends a pickle frame: only
        # peers that speak pickle can decode KIND_BATCH, so pushes and
        # responses to JSON-only peers (the C++ client) stay plain.
        self.peer_pickle = False
        self._sender = (_CoalescingSender(sock, self.send_lock)
                        if batching_enabled() else None)

    def _post(self, kind: int, req_id: int, payload: bytes):
        if self._sender is not None and self.peer_pickle:
            self._sender.send(kind, req_id, payload)
        else:
            with self.send_lock:
                _send_frame(self.sock, kind, req_id, payload)

    def push(self, msg: Any):
        """One-way server→client message."""
        oob, payload = _encode_payload(msg)
        if oob is not None:
            self._post(KIND_OOB, 0, _oob_parts(KIND_ONEWAY, payload))
        else:
            self._post(KIND_ONEWAY, 0, payload)

    def push_json(self, msg: Any):
        """One-way push a non-Python peer can parse (KIND_ONEWAY_JSON)."""
        payload = json.dumps(_to_jsonable(msg)).encode()
        with self.send_lock:
            _send_frame(self.sock, KIND_ONEWAY_JSON, 0, payload)

    def respond(self, req_id: int, msg: Any):
        oob, payload = _encode_payload(msg)
        if oob is not None:
            self._post(KIND_OOB, req_id, _oob_parts(KIND_RESPONSE, payload))
        else:
            self._post(KIND_RESPONSE, req_id, payload)

    def flush_sends(self):
        """Fence: block until buffered pushes/responses hit the socket."""
        if self._sender is not None:
            self._sender.flush()

    def close(self):
        self.alive = False
        if self._sender is not None:
            try:
                self._sender.flush()
            except (RpcError, OSError):
                pass
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class Deferred:
    """Deferred response for long-running ops (pickle-frame requests
    only): return one from a server handler to free the connection's
    serve loop immediately; call resolve()/reject() from any thread to
    send the reply. Resolution before bind() (handler still returning)
    is buffered; double-resolution is ignored."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conn: Optional[Connection] = None
        self._req_id: Optional[int] = None
        self._outcome = None  # ("ok", v) | ("err", e) buffered pre-bind

    def bind(self, conn: "Connection", req_id: int):
        with self._lock:
            outcome = self._outcome
            if outcome is None:
                self._conn, self._req_id = conn, req_id
                return
            self._outcome = None
        # Resolved before bind: reply now; conn is never stored, so a
        # concurrent second resolution can't double-send.
        try:
            conn.respond(req_id, outcome)
        except Exception as exc:
            # The caller never gets its reply — surface it (rate-limited)
            # so a hung client is diagnosable instead of a silent stall.
            warn_once(logger, "deferred-respond", exc,
                      "dropped deferred response req_id=%s (peer gone?)",
                      req_id)

    def resolve(self, value: Any):
        self._finish(("ok", value))

    def reject(self, error: BaseException):
        self._finish(("err", error))

    def _finish(self, outcome):
        with self._lock:
            if self._conn is None:
                if self._outcome is None:
                    self._outcome = outcome
                return
            conn, req_id = self._conn, self._req_id
            self._conn = None  # double-resolve becomes a no-op
        try:
            conn.respond(req_id, outcome)
        except Exception as exc:
            warn_once(logger, "deferred-respond", exc,
                      "dropped deferred response req_id=%s (peer gone?)",
                      req_id)


class Server:
    """Threaded RPC server.

    handler(conn, msg) -> response | None. Called on a per-connection thread;
    long handlers should offload (or return a Deferred).  on_disconnect(conn)
    fires when a peer drops — the raylet's worker-death detection hook.
    """

    def __init__(
        self,
        handler: Callable[[Connection, Any], Any],
        host: str = "127.0.0.1",
        port: int = 0,
        on_disconnect: Optional[Callable[[Connection], None]] = None,
        json_validator: Optional[Callable[[Any], None]] = None,
    ):
        self._handler = handler
        self._on_disconnect = on_disconnect
        # Schema check applied to KIND_REQUEST_JSON frames only — the
        # cross-language door accepts frames from non-Python peers, so
        # it validates against the typed contract (core/wire_schema.py)
        # before dispatch; pickle frames come from our own runtime.
        self._json_validator = json_validator
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(512)
        self.host, self.port = self._sock.getsockname()
        self._stopped = threading.Event()
        self._conns: list[Connection] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            if self._stopped.is_set():
                # Raced with stop(): this fd may already belong to a NEW
                # server (the kernel reuses fds); do not serve it here.
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Connection(sock, addr)
            self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), name="rpc-conn", daemon=True
            ).start()

    def _serve_conn(self, conn: Connection):
        try:
            while not self._stopped.is_set():
                kind, req_id, payload = _recv_frame(conn.sock)
                nbytes = _FRAME.size + len(payload)
                if kind == KIND_BATCH:
                    conn.peer_pickle = True
                    entries = pickle.loads(payload)
                    WIRE.on_frame_received(kind, nbytes, len(entries))
                    for sub_kind, sub_id, sub_payload in entries:
                        if sub_kind in (KIND_BATCH, KIND_BATCH_JSON):
                            continue  # batches never nest
                        self._dispatch(conn, sub_kind, sub_id, sub_payload)
                elif kind == KIND_BATCH_JSON:
                    entries = json.loads(payload)
                    WIRE.on_frame_received(kind, nbytes, len(entries))
                    for entry in entries:
                        sub_kind, sub_id, raw = entry
                        if sub_kind != KIND_REQUEST_JSON:
                            continue
                        self._handle_json(conn, sub_id, raw)
                elif kind == KIND_OOB:
                    conn.peer_pickle = True
                    WIRE.on_frame_received(kind, nbytes)
                    inner_kind, msg = _decode_oob(payload)
                    self._dispatch(conn, inner_kind, req_id, None,
                                   msg=msg)
                else:
                    WIRE.on_frame_received(kind, nbytes)
                    self._dispatch(conn, kind, req_id, payload)
        except (RpcError, OSError, EOFError):
            pass
        finally:
            conn.alive = False
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
            if self._on_disconnect is not None and not self._stopped.is_set():
                try:
                    self._on_disconnect(conn)
                except Exception as exc:
                    # A failing disconnect hook silently breaks worker-death
                    # detection (leases never revoked, actors never failed
                    # over) — that must never be invisible.
                    warn_once(logger, "disconnect-hook", exc,
                              "on_disconnect hook raised for peer %s",
                              getattr(conn, "peername", "?"))

    def _dispatch(self, conn: Connection, kind: int, req_id: int,
                  payload, msg=None):
        """Handle one (possibly batch-unpacked) frame.  Semantics match
        the pre-batching serve loop exactly — a failing sub-request in a
        batch responds ("err", e) like any failing request.  KIND_OOB
        frames arrive pre-decoded (payload None, msg set)."""
        if kind == KIND_REQUEST_JSON:
            self._handle_json(conn, req_id, payload)
            return
        conn.peer_pickle = True
        if payload is not None:
            msg = pickle.loads(payload)
        if kind == KIND_REQUEST:
            try:
                result = self._handler(conn, msg)
                if isinstance(result, Deferred):
                    # Long-running op: the handler parks the response;
                    # another thread resolves it later.  This
                    # connection's serve loop moves on so the client's
                    # other in-flight calls aren't head-of-line blocked.
                    result.bind(conn, req_id)
                    return
                conn.respond(req_id, ("ok", result))
            except Exception as e:  # noqa: BLE001
                conn.respond(req_id, ("err", e))
        else:
            try:
                self._handler(conn, msg)
            except Exception:
                import traceback

                traceback.print_exc()

    def _handle_json(self, conn: Connection, req_id: int, raw: Any):
        """One KIND_REQUEST_JSON message (standalone or from a JSON
        batch): validate against the wire schema, dispatch, respond with
        its own JSON KIND_RESPONSE frame.  `raw` is the undecoded
        payload bytes for standalone frames (malformed JSON must come
        back as an err response, not kill the connection) or the
        already-parsed document for batch entries."""
        try:
            if isinstance(raw, (bytes, bytearray)):
                raw = json.loads(raw)
            msg = _from_jsonable(raw)
            if self._json_validator is not None:
                self._json_validator(msg)
            result = self._handler(conn, msg)
            # allow_nan=False: bare NaN/Infinity tokens are invalid
            # JSON for non-Python peers.
            out = json.dumps({"status": "ok",
                              "result": _to_jsonable(result)},
                             allow_nan=False)
        except Exception as e:  # noqa: BLE001
            out = json.dumps({
                "status": "err",
                "error": f"{type(e).__name__}: {e}"})
        with conn.send_lock:
            _send_frame(conn.sock, KIND_RESPONSE, req_id, out.encode())

    def stop(self):
        self._stopped.set()
        # shutdown() (not just close()) wakes the blocking accept(); a bare
        # close() leaves the accept thread alive, and once the kernel reuses
        # the fd that stale thread would steal another server's connections.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)
        for conn in self._conns:
            conn.close()


class _PendingCall:
    """Handle to one in-flight request: result() blocks for the reply,
    discard() abandons it (a late reply for a forgotten id is dropped by
    the recv loop).  The unit of request pipelining — callers keep
    several outstanding on one connection (windowed object pulls)."""

    __slots__ = ("_client", "_req_id", "_ev")

    def __init__(self, client: "Client", req_id: int, ev: threading.Event):
        self._client = client
        self._req_id = req_id
        self._ev = ev

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._ev.wait(timeout):
            self.discard()
            raise TimeoutError(f"rpc call timed out after {timeout}s")
        self._client._pending.pop(self._req_id, None)
        status, result = self._client._results.pop(self._req_id)
        if status == "err":
            raise result
        return result

    def discard(self):
        self._client._pending.pop(self._req_id, None)
        self._client._results.pop(self._req_id, None)


class Client:
    """Thread-safe RPC client with request/response matching and push inbox."""

    def __init__(
        self,
        address: str,
        on_push: Optional[Callable[[Any], None]] = None,
        connect_timeout: float = 10.0,
        on_disconnect: Optional[Callable[[], None]] = None,
    ):
        self._on_disconnect = on_disconnect
        host, port = address.rsplit(":", 1)
        deadline = time.monotonic() + connect_timeout
        last_err: Exception | None = None
        while True:
            try:
                # raylint: allow-blocking(construction-time dial; op handlers build node/actor clients once and cache them)
                self._sock = socket.create_connection((host, int(port)), timeout=5.0)
                break
            except OSError as e:
                last_err = e
                if time.monotonic() >= deadline:
                    raise RpcError(f"cannot connect to {address}: {e}") from e
                # raylint: allow-blocking(bounded redial backoff during construction only)
                time.sleep(0.05)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.address = address
        self._on_push = on_push
        # Optional hook run before every synchronous call(): lets the
        # core runtime flush coalesced one-way sends so request/response
        # ops observe everything submitted before them (runtime.py).
        self._pre_call: Optional[Callable[[], None]] = None
        self._send_lock = threading.Lock()
        # Wire coalescing (KIND_BATCH): requests AND one-ways share one
        # FIFO buffer so total send order is preserved — the runtime
        # relies on a call() observing every send() issued before it.
        self._sender = (_CoalescingSender(self._sock, self._send_lock)
                        if batching_enabled() else None)
        # Legacy-path counters so frames_sent stays meaningful (and the
        # burst-regression test stays expressible) under NO_BATCH.
        self._plain_frames = 0
        self._pending: dict[int, threading.Event] = {}
        self._results: dict[int, Any] = {}
        self._next_id = 1
        self._id_lock = threading.Lock()
        self._closed = False
        self._recv_thread = threading.Thread(
            target=self._recv_loop, name="rpc-client-recv", daemon=True
        )
        self._recv_thread.start()

    def _recv_loop(self):
        try:
            while True:
                kind, req_id, payload = _recv_frame(self._sock)
                nbytes = _FRAME.size + len(payload)
                if kind == KIND_BATCH:
                    entries = pickle.loads(payload)
                    WIRE.on_frame_received(kind, nbytes, len(entries))
                    for sub_kind, sub_id, sub_payload in entries:
                        if sub_kind in (KIND_BATCH, KIND_BATCH_JSON):
                            continue  # batches never nest
                        self._on_frame(sub_kind, sub_id, sub_payload)
                elif kind == KIND_OOB:
                    WIRE.on_frame_received(kind, nbytes)
                    inner_kind, msg = _decode_oob(payload)
                    self._on_msg(inner_kind, req_id, msg)
                else:
                    WIRE.on_frame_received(kind, nbytes)
                    self._on_frame(kind, req_id, payload)
        except (RpcError, OSError, EOFError):
            was_closed = self._closed
            self._closed = True
            err = ("err", RpcError(f"connection to {self.address} lost"))
            for req_id, ev in list(self._pending.items()):
                self._results[req_id] = err
                ev.set()
            # Fire only on an UNEXPECTED loss (close() sets _closed
            # before shutting the socket down).
            if not was_closed and self._on_disconnect is not None:
                try:
                    self._on_disconnect()
                except Exception:
                    import traceback

                    traceback.print_exc()

    def _on_frame(self, kind: int, req_id: int, payload: bytes):
        self._on_msg(kind, req_id, pickle.loads(payload))

    def _on_msg(self, kind: int, req_id: int, msg: Any):
        if kind == KIND_RESPONSE:
            ev = self._pending.get(req_id)
            if ev is not None:
                self._results[req_id] = msg
                ev.set()
        elif kind == KIND_ONEWAY and self._on_push is not None:
            try:
                self._on_push(msg)
            except Exception:
                import traceback

                traceback.print_exc()

    def _post(self, kind: int, req_id: int, payload: bytes,
              wait: bool = False):
        if self._sender is not None:
            self._sender.send(kind, req_id, payload, wait=wait)
        else:
            with self._send_lock:
                _send_frame(self._sock, kind, req_id, payload)
                self._plain_frames += 1

    @property
    def frames_sent(self) -> int:
        """Control-plane frames written to this socket (telemetry for
        the burst-submission regression test and the RPC bench probe)."""
        s = self._sender
        return self._plain_frames if s is None else s.frames_sent

    @property
    def msgs_sent(self) -> int:
        s = self._sender
        return self._plain_frames if s is None else s.msgs_sent

    @property
    def batches_sent(self) -> int:
        s = self._sender
        return 0 if s is None else s.batches_sent

    def flush_sends(self):
        """Fence: block until every previously enqueued frame is on the
        socket.  No-op without coalescing (sends are then synchronous)."""
        if self._sender is not None:
            self._sender.flush()

    def call_async(self, msg: Any) -> _PendingCall:
        """Post a request and return a handle without waiting for the
        reply.  Multiple handles may be outstanding on one connection
        (responses match by request id) — the windowed object pull keeps
        a whole window of these in flight."""
        if self._closed:
            raise RpcError(f"connection to {self.address} closed")
        if self._pre_call is not None:
            self._pre_call()
        with self._id_lock:
            req_id = self._next_id
            self._next_id += 1
        ev = threading.Event()
        self._pending[req_id] = ev
        oob, payload = _encode_payload(msg)
        if oob is not None:
            self._post(KIND_OOB, req_id, _oob_parts(KIND_REQUEST, payload))
        else:
            self._post(KIND_REQUEST, req_id, payload)
        return _PendingCall(self, req_id, ev)

    def call(self, msg: Any, timeout: Optional[float] = None) -> Any:
        return self.call_async(msg).result(timeout)

    def send(self, msg: Any, wait: bool = False):
        """One-way message.  wait=True blocks until the bytes are on
        the socket — callers whose flow control assumes a blocking send
        (object-plane chunk streaming) keep their backpressure."""
        if self._closed:
            raise RpcError(f"connection to {self.address} closed")
        oob, payload = _encode_payload(msg)
        if oob is not None:
            self._post(KIND_OOB, 0, _oob_parts(KIND_ONEWAY, payload),
                       wait=wait)
        else:
            self._post(KIND_ONEWAY, 0, payload, wait=wait)

    def close(self):
        self._closed = True
        # Drain buffered frames before tearing the socket down: the
        # legacy (synchronous-send) protocol never lost tail messages
        # on a clean close, and final decref/task_done traffic matters.
        if self._sender is not None:
            try:
                self._sender.flush()
            except (RpcError, OSError):
                pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
