"""Typed identifiers for tasks, actors, objects, nodes and workers.

Capability counterpart of the reference's typed-ID layer
(src/ray/common/id.h): fixed-width random binary IDs with hex rendering,
hashable and order-stable so they can key tables in the control store and be
shipped over the wire cheaply.  TPU-native design note: IDs are plain bytes —
no embedded job/actor cursors — because ownership metadata lives in the
object directory rather than being bit-packed into the ID.
"""

from __future__ import annotations

import os
import threading

_ID_NBYTES = 14


# Unique-ID generation: one urandom seed per (process, thread), then a
# counter suffix — keeps from_random() syscall-free on the hot submission
# paths (two IDs per task) while preserving global uniqueness.
_rand_local = threading.local()


def _next_unique() -> bytes:
    st = _rand_local
    try:
        n = st.counter
    except AttributeError:
        st.suffix = os.urandom(_ID_NBYTES - 6)
        st.counter = n = int.from_bytes(os.urandom(6), "little")
    st.counter = (n + 1) & 0xFFFFFFFFFFFF
    # Counter bytes FIRST: consumers hash id prefixes (e.g. the SPREAD
    # tie-break), so the varying part must lead; the per-thread random
    # suffix carries the uniqueness across processes/threads.
    return n.to_bytes(6, "little") + st.suffix


class BaseID:
    __slots__ = ("_bytes", "_hex", "_hashv")
    _prefix = "id"

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != _ID_NBYTES:
            raise ValueError(
                f"{type(self).__name__} requires {_ID_NBYTES} bytes, got {binary!r}"
            )
        self._bytes = binary
        self._hex = None
        self._hashv = None

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_NBYTES)

    @classmethod
    def from_random(cls):
        return cls(_next_unique())

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        h = self._hex
        if h is None:
            h = self._hex = self._bytes.hex()
        return h

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * _ID_NBYTES

    def __hash__(self):
        # Cached: ids key hot dicts/sets (wait sets, arg prep) and the
        # tuple construction + double hash dominated profiles.
        h = self._hashv
        if h is None:
            h = self._hashv = hash((self._prefix, self._bytes))
        return h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class TaskID(BaseID):
    _prefix = "task"


class ObjectID(BaseID):
    _prefix = "obj"


class ActorID(BaseID):
    _prefix = "actor"


class NodeID(BaseID):
    _prefix = "node"


class WorkerID(BaseID):
    _prefix = "worker"


class JobID(BaseID):
    _prefix = "job"


class PlacementGroupID(BaseID):
    _prefix = "pg"


class _SequenceGen:
    """Monotonic per-process sequence numbers (actor task ordering)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0

    def next(self) -> int:
        with self._lock:
            v = self._next
            self._next += 1
            return v
