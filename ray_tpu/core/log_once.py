"""Rate-limited once-per-cause warnings for hot paths.

The control and object planes have many best-effort steps (respond to a
peer that may have hung up, cache a pulled object in the local arena,
notify an optional hook) where raising is wrong but silence is worse:
PR 3's arena cache ate every failure and a full arena was undiagnosable
— each read silently re-pulled over the wire.  The fix pattern — warn
once per distinct cause per interval — is now the house rule enforced
by raylint's exception-hygiene pass; this module is its shared
implementation so fixed swallow sites don't each re-grow a private
lock + table.

Usage, replacing ``except Exception: pass``::

    from ray_tpu.core.log_once import warn_once
    ...
    except Exception as exc:
        warn_once(logger, "respond-failed", exc,
                  "could not deliver response (peer gone?)")

A (tag, exception type, truncated message) triple is warned at most
once per ``_WARN_INTERVAL_S``; repeats within the window are counted
and the count is folded into the next emission so bursts stay visible
without log spam.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

_WARN_INTERVAL_S = 60.0
_lock = threading.Lock()
# cause-key -> (last emission monotonic time, suppressed since then)
_seen: Dict[str, Tuple[float, int]] = {}


def cause_key(tag: str, exc: Optional[BaseException]) -> str:
    if exc is None:
        return tag
    return f"{tag}: {type(exc).__name__}: {str(exc)[:120]}"


def should_log(tag: str, exc: Optional[BaseException] = None,
               interval_s: float = _WARN_INTERVAL_S
               ) -> Tuple[bool, int]:
    """(emit?, count suppressed since the last emission).  Thread-safe
    and allocation-light: one dict probe under one module lock."""
    key = cause_key(tag, exc)
    now = time.monotonic()
    with _lock:
        last = _seen.get(key)
        if last is not None and now - last[0] < interval_s:
            _seen[key] = (last[0], last[1] + 1)
            return False, 0
        suppressed = last[1] if last is not None else 0
        _seen[key] = (now, 0)
    return True, suppressed


def warn_once(logger, tag: str, exc: Optional[BaseException],
              message: str, *args,
              interval_s: float = _WARN_INTERVAL_S) -> bool:
    """Log ``message`` (lazy %-args) at WARNING, at most once per
    distinct (tag, cause) per interval.  Returns True if it logged."""
    emit, suppressed = should_log(tag, exc, interval_s)
    if not emit:
        return False
    suffix = f" [{suppressed} similar suppressed]" if suppressed else ""
    cause = f": {cause_key('', exc)[2:]}" if exc is not None else ""
    logger.warning(message + cause + suffix, *args)
    return True


def reset() -> None:
    """Test hook: forget every cause."""
    with _lock:
        _seen.clear()
