"""Serialization: cloudpickle envelope with zero-copy out-of-band buffers.

Capability counterpart of the reference's SerializationContext
(python/ray/_private/serialization.py): cloudpickle for arbitrary Python,
pickle protocol-5 out-of-band buffers so numpy / jax host arrays are written
into the shared-memory object store without an extra copy, and ObjectRef
capture hooks so refs nested inside values keep their identity (the borrowing
protocol hook point).

Wire layout of a serialized object:

    [8-byte header length][msgpack header][payload][buf0][buf1]...

header = {"pkl_len": int, "bufs": [int, ...], "refs": [hex, ...]}
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Callable

import cloudpickle
import msgpack
import numpy as np

_HEADER_FMT = "<Q"
_HEADER_LEN = struct.calcsize(_HEADER_FMT)

# Debug escape hatch: copy out-of-band buffers on deserialize instead of
# aliasing the source (shm mmap / message bytes).
import os as _os

_COPY_BUFFERS = _os.environ.get("RAY_TPU_COPY_DESER_BUFFERS", "") == "1"


class SerializedObject:
    """A serialized value plus its out-of-band buffers (not yet concatenated)."""

    __slots__ = ("header_bytes", "payload", "buffers", "contained_refs")

    def __init__(self, header_bytes: bytes, payload: bytes, buffers, contained_refs):
        self.header_bytes = header_bytes
        self.payload = payload
        self.buffers = buffers
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return (
            _HEADER_LEN
            + len(self.header_bytes)
            + len(self.payload)
            + sum(len(b) for b in self.buffers)
        )

    def write_into(self, view: memoryview) -> None:
        """Copy the object into a contiguous writable buffer (e.g. shm)."""
        off = 0
        view[off:off + _HEADER_LEN] = struct.pack(_HEADER_FMT, len(self.header_bytes))
        off += _HEADER_LEN
        view[off:off + len(self.header_bytes)] = self.header_bytes
        off += len(self.header_bytes)
        view[off:off + len(self.payload)] = self.payload
        off += len(self.payload)
        for b in self.buffers:
            n = len(b)
            view[off:off + n] = b.cast("B") if isinstance(b, memoryview) else memoryview(b).cast("B")
            off += n

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_bytes)
        self.write_into(memoryview(out))
        return bytes(out)


def serialize(value: Any, ref_serializer: Callable | None = None) -> SerializedObject:
    """Serialize ``value``.

    ref_serializer(obj) -> hex string is invoked for every ObjectRef found
    inside the value so the owner can track borrowed references.

    Plain C-contiguous numpy arrays and bytes take a RAW fast path: the
    header describes the dtype/shape and the value's own buffer ships
    out-of-band, so the only copy the object ever sees is the single
    source->arena write in write_into (the create/seal in-place write
    the reference gets from plasma's C++ client).  cloudpickle costs
    ~100 us per call even for an ndarray — at put-microbench rates that
    was the single biggest line (VERDICT r3 "put path below baseline").
    """
    t = type(value)
    if t is np.ndarray and value.dtype.kind in "biufc" \
            and value.flags.c_contiguous:
        header = msgpack.packb({
            "pkl_len": 0, "bufs": [value.nbytes], "refs": [],
            "nd": [value.dtype.str, list(value.shape)],
        })
        return SerializedObject(header, b"", [memoryview(value).cast("B")],
                                [])
    if t is bytes:
        header = msgpack.packb({
            "pkl_len": 0, "bufs": [len(value)], "refs": [], "rawb": 1,
        })
        return SerializedObject(header, b"", [value], [])
    buffers: list[memoryview] = []

    def buffer_callback(buf):
        buffers.append(buf.raw())
        return False  # out-of-band

    # ObjectRef.__reduce__ appends every ref pickled inside ``value`` to the
    # thread-local capture list, so nested refs keep identity and the owner
    # can track borrows (the reference's out-of-band ObjectRef capture,
    # python/ray/_private/serialization.py).
    contained: list[str] = []
    from ray_tpu.core import object_ref as _orf

    token = _orf._push_capture_list(contained)
    try:
        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
    finally:
        _orf._pop_capture_list(token)

    header = msgpack.packb(
        {
            "pkl_len": len(payload),
            "bufs": [len(b) for b in buffers],
            "refs": contained,
        }
    )
    return SerializedObject(header, payload, buffers, contained)


def deserialize(data, ref_deserializer: Callable | None = None) -> Any:
    """Deserialize from a contiguous buffer (bytes or memoryview).

    Buffers are reconstructed zero-copy as memoryviews into ``data`` — numpy
    arrays deserialized from shm alias the store segment until copied.
    """
    view = memoryview(data)
    (hlen,) = struct.unpack(_HEADER_FMT, view[:_HEADER_LEN])
    off = _HEADER_LEN
    header = msgpack.unpackb(view[off:off + hlen])
    off += hlen
    nd = header.get("nd")
    if nd is not None:
        # RAW ndarray fast path: reconstruct as a zero-copy view over
        # the buffer (aliasing shm until copied, same contract as the
        # pickle5 out-of-band path below).
        blen = header["bufs"][0]
        buf = bytes(view[off:off + blen]) if _COPY_BUFFERS \
            else view[off:off + blen]
        dtype, shape = np.dtype(nd[0]), tuple(nd[1])
        return np.frombuffer(buf, dtype=dtype).reshape(shape)
    if header.get("rawb"):
        blen = header["bufs"][0]
        return bytes(view[off:off + blen])
    payload = view[off:off + header["pkl_len"]]
    off += header["pkl_len"]
    bufs = []
    for blen in header["bufs"]:
        if _COPY_BUFFERS:
            bufs.append(pickle.PickleBuffer(bytes(view[off:off + blen])))
        else:
            bufs.append(pickle.PickleBuffer(view[off:off + blen]))
        off += blen
    from ray_tpu.core import object_ref as _orf

    token = _orf._push_ref_resolver(ref_deserializer)
    try:
        return pickle.loads(payload, buffers=bufs)
    finally:
        _orf._pop_ref_resolver(token)


def contained_refs(data) -> list[str]:
    view = memoryview(data)
    (hlen,) = struct.unpack(_HEADER_FMT, view[:_HEADER_LEN])
    header = msgpack.unpackb(view[_HEADER_LEN:_HEADER_LEN + hlen])
    return header.get("refs", [])
