"""Object-plane transfer managers: demand pulls and push broadcast.

Counterpart of the reference's PushManager/PullManager pair
(src/ray/object_manager/object_manager.h:206 — push_manager chunk
scheduling; pull_manager.h:52 — memory-bounded admission).

PULL side (`PullManager` + `pull_into_store`): demand-driven transfer
used by runtime._pull_remote_object against node_manager/head
`fetch_chunk` servers.  Chunks are windowed (rpc.pull_object_chunked)
and land DIRECTLY in a pre-created arena segment — no full-size
intermediate buffer, no extra copy on the cache path — and concurrent
pulls of one object are single-flighted: the first caller drives the
wire, everyone else waits on its outcome and attaches to the sealed
segment (reference pull_manager.h request coalescing).

PUSH side (`PushManager`) — one source fans an object's chunks out to N
node arenas concurrently, under a global in-flight byte budget, so a
1-GiB broadcast to a cluster neither serializes per node nor floods
memory/sockets.

Admission control exists on BOTH ends:
  - sender: a byte-budget semaphore caps the total chunk payload in
    flight across every destination (the PullManager idea applied to
    pushes); destinations stream independently, so one slow or dead
    node never stalls the others.
  - receiver: `push_begin` allocates the object up front from the
    node's arena and REJECTS (not blocks) when the arena can't hold
    it; partial transfers are reaped by age so an aborted sender never
    leaks arena memory.

Failure model: per-destination isolation.  A node dying mid-broadcast
fails that one destination (reported in the result map); the remaining
destinations complete — pinned by tests/test_chaos.py.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.log_once import warn_once

logger = logging.getLogger(__name__)

# Cached lazy import: util/__init__ pulls in placement groups → runtime
# → this module, so a top-level flight-recorder import here would cycle
# (same shape as rpc._flight_recorder).
_flight = None


def _flight_recorder():
    global _flight
    if _flight is None:
        try:
            from ray_tpu.util import flight_recorder as fr

            _flight = fr
        except Exception:
            _flight = False
    return _flight


def _record(event: str, **fields) -> None:
    fr = _flight_recorder()
    if fr:
        fr.record("object", event, **fields)


class _ObjPlaneStats:
    """Process-wide object-plane telemetry, exported through
    util/metrics.py via object_metric_snapshots() — same pattern as
    rpc._WireStats: a module-level singleton so the transfer hot path
    never touches the metrics registry."""

    def __init__(self):
        self.lock = threading.Lock()
        self.bytes_pulled = 0
        self.bytes_pushed = 0
        self.pulls_started = 0
        self.pulls_deduped = 0
        self.pull_errors = 0
        self.arena_cache_hits = 0
        self.arena_cache_stores = 0
        self.arena_cache_failures = 0

    def _inc(self, field: str, n: int = 1):
        with self.lock:
            setattr(self, field, getattr(self, field) + n)


OBJ = _ObjPlaneStats()


def object_metric_snapshots() -> list:
    """This process's object-plane counters as metric-snapshot dicts in
    the util/metrics.py exposition shape (merged into local_snapshots()).
    Locality-hit counting lives head-side in gcs.py as a registry
    Counter — placement decisions only happen there."""
    o = OBJ
    with o.lock:
        bytes_pulled, bytes_pushed = o.bytes_pulled, o.bytes_pushed
        started, deduped, errors = (o.pulls_started, o.pulls_deduped,
                                    o.pull_errors)
        hits, stores, failures = (o.arena_cache_hits,
                                  o.arena_cache_stores,
                                  o.arena_cache_failures)
    return [
        {"name": "object_transfer_bytes_total", "kind": "counter",
         "description": "Object-plane payload bytes moved between nodes",
         "series": {(("direction", "pulled"),): float(bytes_pulled),
                    (("direction", "pushed"),): float(bytes_pushed)}},
        {"name": "object_pulls_total", "kind": "counter",
         "description": "Object pulls by outcome (deduped = coalesced "
                        "onto an in-flight pull of the same object)",
         "series": {(("result", "started"),): float(started),
                    (("result", "deduped"),): float(deduped),
                    (("result", "error"),): float(errors)}},
        {"name": "object_arena_cache_total", "kind": "counter",
         "description": "Local-arena replica cache events for remote "
                        "objects (hit = later read served from shm)",
         "series": {(("event", "hit"),): float(hits),
                    (("event", "store"),): float(stores),
                    (("event", "failure"),): float(failures)}},
    ]


# -- rate-limited arena-cache diagnostics -----------------------------------
# Caching a pulled object into the local arena is best-effort, but the
# old bare `except Exception: pass` made a persistently full arena
# undiagnosable (every read re-pulled over the wire, silently).  Warn
# once per distinct cause per interval (shared impl: core/log_once.py).


def _warn_arena_cache(exc: BaseException, obj_hex: str = "") -> None:
    OBJ._inc("arena_cache_failures")
    warn_once(logger, "arena-cache", exc,
              "could not cache pulled object %s in the local arena "
              "(reads will keep pulling over the wire)",
              obj_hex or "<unknown>")


class PullManager:
    """Single-flight table for concurrent pulls of one object
    (reference pull_manager.h request coalescing): the first caller
    becomes the leader and drives the wire; callers arriving while that
    pull is in flight wait on its event and share the outcome.  An
    error propagates to every waiter, and the entry is cleared BEFORE
    waiters wake so a retry re-pulls instead of joining the corpse."""

    class _Flight:
        __slots__ = ("done", "result", "error")

        def __init__(self):
            self.done = threading.Event()
            self.result: Any = None
            self.error: Optional[BaseException] = None

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: Dict[str, "PullManager._Flight"] = {}

    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def pull(self, obj_hex: str, fn: Callable[[], Any],
             timeout: float = 600.0) -> Any:
        """Run `fn` single-flighted under `obj_hex`; concurrent callers
        for the same key block on the leader and receive its result (or
        its exception)."""
        with self._lock:
            fl = self._inflight.get(obj_hex)
            if fl is None:
                fl = self._Flight()
                self._inflight[obj_hex] = fl
                leader = True
            else:
                leader = False
        if not leader:
            OBJ._inc("pulls_deduped")
            _record("dedup_join", obj=obj_hex)
            if not fl.done.wait(timeout):
                raise TimeoutError(
                    f"waited {timeout}s on an in-flight pull of "
                    f"{obj_hex}")
            if fl.error is not None:
                raise fl.error
            return fl.result
        try:
            fl.result = fn()
            return fl.result
        except BaseException as e:
            fl.error = e
            raise
        finally:
            # Clear the entry first, wake waiters second: a waiter that
            # sees the error and retries must start a FRESH flight.
            with self._lock:
                self._inflight.pop(obj_hex, None)
            fl.done.set()


def pull_into_store(client, store, obj_hex: str, size: int, chunk: int,
                    *, window: Optional[int] = None,
                    timeout: float = 120.0) -> Tuple[Any, bool]:
    """Pull an object's bytes from the peer behind `client`, landing
    chunks directly in a pre-created local arena segment (reference
    ObjectBufferPool: chunks write into the plasma allocation, not an
    intermediate buffer).  Returns (data, cached): `data` is a buffer
    of the payload (a zero-copy view of the sealed segment when caching
    succeeded), `cached` says whether the local store now holds a
    replica.

    Failure model: a wire error mid-pull deletes the partial segment
    (nothing half-written survives in the arena) and re-raises; arena
    failures (full, race) degrade to an uncached in-memory pull with a
    rate-limited warning.
    """
    OBJ._inc("pulls_started")
    peer = getattr(client, "address", "")
    _record("pull_begin", obj=obj_hex,
                           peer=peer, bytes=size)
    t0 = time.monotonic()
    from ray_tpu.core import rpc

    oid = ObjectID.from_hex(obj_hex)
    seg = None
    if store is not None:
        try:
            seg = store.create(oid, size)
        except Exception as e:  # noqa: BLE001 — cache is best-effort
            _warn_arena_cache(e, obj_hex)
    try:
        if seg is None:
            data = rpc.pull_object_chunked(client, obj_hex, size, chunk,
                                           timeout=timeout, window=window)
            cached = False
        else:
            try:
                rpc.pull_object_chunked(client, obj_hex, size, chunk,
                                        timeout=timeout, window=window,
                                        into=seg.buf)
            except BaseException:
                # Reap the partial segment: an aborted pull must not
                # leave a half-written object for attach() to find.
                try:
                    store.delete(oid)
                except Exception as e:  # noqa: BLE001
                    # A reap failure leaks an arena block per aborted
                    # pull — that slow leak must be visible.
                    warn_once(logger, "arena-reap", e,
                              "could not reap partial segment for %s",
                              obj_hex)
                raise
            data, cached = _seal_and_reattach(store, oid, obj_hex, size,
                                              seg)
    except BaseException:
        OBJ._inc("pull_errors")
        _record("pull_end", obj=obj_hex,
                               peer=peer, bytes=size, ok=False,
                               duration_s=round(time.monotonic() - t0, 6))
        raise
    OBJ._inc("bytes_pulled", size)
    if cached:
        OBJ._inc("arena_cache_stores")
    _record("pull_end", obj=obj_hex, peer=peer,
                           bytes=size, ok=True, cached=cached,
                           duration_s=round(time.monotonic() - t0, 6))
    return data, cached


def _seal_and_reattach(store, oid, obj_hex: str, size: int,
                       seg) -> Tuple[Any, bool]:
    """Seal a fully-written segment and return a fresh read view.
    seal() evicts the creator's writable view in the native arena (its
    block may be reused once the create pin drops), so the bytes MUST
    be re-read through attach()."""
    try:
        store.seal(oid)
    except Exception as e:  # noqa: BLE001
        # Unsealed: the creator's view is still pinned and readable.
        # Copy out, drop the segment, serve uncached.
        _warn_arena_cache(e, obj_hex)
        data = bytes(seg.buf[:size])
        try:
            store.delete(oid)
        except Exception as e2:  # noqa: BLE001
            warn_once(logger, "arena-reap", e2,
                      "could not drop unsealed segment for %s", obj_hex)
        return data, False
    try:
        view = store.attach(oid, size)
        return view.buf[:size], True
    except Exception as e:  # noqa: BLE001
        # Sealed but unreadable here (pin race): the replica EXISTS —
        # report cached=True — but these bytes must come from a copy.
        _warn_arena_cache(e, obj_hex)
        return bytes(seg.buf[:size]), True


class PushManager:
    """Fan one local object's bytes out to peer node arenas."""

    def __init__(self, runtime, *, chunk_bytes: Optional[int] = None,
                 max_inflight_bytes: int = 64 * 1024 * 1024):
        self._rt = runtime
        self.chunk_bytes = max(
            1 << 20, chunk_bytes or runtime.config.transfer_chunk_bytes)
        self._max_inflight_bytes = max_inflight_bytes

    def broadcast(self, obj_hex: str, size: int,
                  destinations: Sequence[str], *,
                  timeout: float = 600.0) -> Dict[str, str]:
        """Push object bytes to every destination address concurrently.

        Returns {address: "ok" | "have" | "reject: ..." | "error: ..."}.
        The source segment is the local arena copy (it must exist
        here); each destination streams independently on its own node
        connection.
        """
        seg = self._rt.store.attach(ObjectID.from_hex(obj_hex), size)
        results: Dict[str, str] = {}
        lock = threading.Lock()
        # PER-DESTINATION budgets, one global total: a destination that
        # stalls inside a blocking send (partitioned peer with the TCP
        # connection held open) can pin at most ITS OWN permits — the
        # documented "one slow or dead node never stalls the others"
        # invariant would not survive a shared semaphore.
        per_dest = max(1, (self._max_inflight_bytes // self.chunk_bytes)
                       // max(1, len(destinations)))
        budgets = {a: threading.BoundedSemaphore(per_dest)
                   for a in destinations}

        def one(addr: str):
            try:
                results_val = self._push_one(addr, obj_hex, size, seg,
                                             timeout, budgets[addr])
            except Exception as e:  # noqa: BLE001 — per-dest isolation
                results_val = f"error: {type(e).__name__}: {e}"
            with lock:
                results[addr] = results_val

        threads = [threading.Thread(target=one, args=(a,), daemon=True,
                                    name=f"push-{a}")
                   for a in destinations]
        for t in threads:
            t.start()
        # ONE deadline across every join — sequential full-timeout joins
        # would make the worst case len(destinations) * timeout.
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        for a in destinations:
            with lock:
                results.setdefault(a, "error: timeout")
        return results

    def _push_one(self, addr: str, obj_hex: str, size: int, seg,
                  timeout: float, budget) -> str:
        t0 = time.monotonic()
        _record("push_begin", obj=obj_hex,
                               peer=addr, bytes=size)
        try:
            status = self._push_one_inner(addr, obj_hex, size, seg,
                                          timeout, budget)
        except BaseException as e:
            _record(
                "push_end", obj=obj_hex, peer=addr, bytes=size,
                ok=False, status=f"error: {type(e).__name__}",
                duration_s=round(time.monotonic() - t0, 6))
            raise
        if status in ("ok", "have"):
            if status == "ok":
                OBJ._inc("bytes_pushed", size)
            _record(
                "push_end", obj=obj_hex, peer=addr, bytes=size,
                ok=True, status=status,
                duration_s=round(time.monotonic() - t0, 6))
        else:
            _record(
                "push_end", obj=obj_hex, peer=addr, bytes=size,
                ok=False, status=status,
                duration_s=round(time.monotonic() - t0, 6))
        return status

    def _push_one_inner(self, addr: str, obj_hex: str, size: int, seg,
                        timeout: float, budget) -> str:
        from ray_tpu.core import rpc

        conn = self._rt._node_conn(addr)
        begin = conn.call({"op": "push_begin", "obj": obj_hex,
                           "size": size}, timeout=30.0)
        if begin.get("have"):
            return "have"
        if begin.get("reject"):
            return f"reject: {begin['reject']}"
        deadline = time.monotonic() + timeout
        window = rpc.pull_window()
        if window <= 1:
            self._stream_legacy(conn, addr, obj_hex, size, seg, budget,
                                deadline)
        else:
            self._stream_windowed(conn, addr, obj_hex, size, seg,
                                  budget, deadline, timeout, window)
        reply = conn.call({"op": "push_end", "obj": obj_hex},
                          timeout=timeout)
        if not (reply or {}).get("ok"):
            return f"error: {(reply or {}).get('error', 'push_end failed')}"
        return "ok"

    def _stream_legacy(self, conn, addr: str, obj_hex: str, size: int,
                       seg, budget, deadline: float) -> None:
        """RAY_TPU_PULL_WINDOW=1: the legacy wire byte for byte —
        ONE-WAY chunk frames, serialized by the blocking send.  The TCP
        stream orders chunks, a blocking send applies receiver
        backpressure, and push_end's byte-count check catches any
        loss.  wait=True keeps the budget accounting honest under rpc
        coalescing (the slot must not be released while the chunk still
        sits in the send buffer)."""
        off = 0
        while off < size:
            n = min(self.chunk_bytes, size - off)
            budget.acquire()
            try:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"broadcast to {addr} timed out")
                conn.send({"op": "push_chunk", "obj": obj_hex,
                           "offset": off,
                           "data": bytes(seg.buf[off:off + n])},
                          wait=True)
            finally:
                budget.release()
            off += n

    def _stream_windowed(self, conn, addr: str, obj_hex: str,
                         size: int, seg, budget, deadline: float,
                         timeout: float, window: int) -> None:
        """Windowed chunk pipeline, mirroring rpc.pull_object_chunked:
        up to `window` push_chunk call_asyncs stay in flight, so the
        peer writes chunk k while chunk k+1 rides the wire — one
        round-trip TOTAL of pipeline fill instead of one serialized
        send per chunk.  The per-destination budget still bounds
        in-flight bytes: a slot is held from issue until the peer's
        ack, and acquire(blocking=False) can only fail while our own
        chunks hold slots, so popping the oldest ack always makes
        progress (no deadlock)."""
        inflight: deque = deque()  # (offset, pending call)
        off = 0
        try:
            while inflight or off < size:
                while off < size and len(inflight) < window \
                        and budget.acquire(blocking=False):
                    if time.monotonic() > deadline:
                        budget.release()
                        raise TimeoutError(
                            f"broadcast to {addr} timed out")
                    n = min(self.chunk_bytes, size - off)
                    try:
                        pending = conn.call_async(
                            {"op": "push_chunk", "obj": obj_hex,
                             "offset": off,
                             "data": bytes(seg.buf[off:off + n])})
                    except BaseException:
                        budget.release()
                        raise
                    inflight.append((off, pending))
                    off += n
                _chunk_off, pending = inflight.popleft()
                try:
                    reply = pending.result(
                        timeout=max(0.1, min(
                            timeout, deadline - time.monotonic())))
                finally:
                    budget.release()
                if reply is not None and reply.get("ok") is False:
                    raise RuntimeError(
                        f"peer rejected chunk at {_chunk_off}")
        except BaseException:
            # Abandon outstanding requests (late acks are dropped by
            # the recv loop) and give their budget slots back.
            while inflight:
                _o, pending = inflight.popleft()
                try:
                    pending.discard()
                finally:
                    budget.release()
            raise


def broadcast_object(ref, node_ids: Optional[List[str]] = None, *,
                     chunk_bytes: Optional[int] = None,
                     max_inflight_bytes: int = 64 * 1024 * 1024,
                     timeout: float = 600.0) -> Dict[str, str]:
    """Push a shm-resident object to other nodes' arenas ahead of use
    (reference `ObjectManager::Push`): consumers there then read shm
    locally instead of pulling over the wire at first access.

    node_ids: target node ids (default: every alive non-head node that
    doesn't already hold a copy).  Returns {node_id: status}.
    """
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    core = getattr(rt, "core", rt)
    obj_hex = ref.hex() if hasattr(ref, "hex") else str(ref)
    info = core.client.call({"op": "object_info", "obj": obj_hex},
                            timeout=30.0)
    if not info or not info.get("in_shm"):
        raise ValueError(
            f"broadcast_object needs a sealed shm object; {obj_hex} is "
            f"{'inline' if info else 'unknown'}")
    holder = info.get("node", "head")
    if holder != core.store_node:
        # The push source streams from the LOCAL arena; a copy living
        # on another node would fail deep inside store.attach with an
        # arena-internal error — say what is actually wrong instead.
        raise ValueError(
            f"broadcast_object must run where the object lives: "
            f"{obj_hex} is in node {holder!r}'s arena, this process is "
            f"on {core.store_node!r} (fetch it locally first, or "
            "broadcast from that node)")
    nodes = core.client.call({"op": "list_nodes"}, timeout=30.0)
    targets = []
    for n in nodes:
        if not n.get("alive") or n.get("is_head"):
            continue
        if node_ids is not None and n["node_id"] not in node_ids:
            continue
        targets.append((n["node_id"], n["address"]))
    pm = PushManager(core, chunk_bytes=chunk_bytes,
                     max_inflight_bytes=max_inflight_bytes)
    by_addr = pm.broadcast(obj_hex, info["size"],
                           [a for _, a in targets], timeout=timeout)
    return {nid: by_addr.get(a, "error: missing")
            for nid, a in targets}
