"""Push-based object broadcast with bounded in-flight admission.

Counterpart of the reference's PushManager/PullManager pair
(src/ray/object_manager/object_manager.h:206 — push_manager chunk
scheduling; pull_manager.h:52 — memory-bounded admission): the pull
side of this stack's object plane (runtime._pull_remote_object →
node_manager `fetch_chunk`) covers demand-driven transfer; this module
adds the PUSH direction — one source fans an object's chunks out to N
node arenas concurrently, under a global in-flight byte budget, so a
1-GiB broadcast to a cluster neither serializes per node nor floods
memory/sockets.

Admission control exists on BOTH ends:
  - sender: a byte-budget semaphore caps the total chunk payload in
    flight across every destination (the PullManager idea applied to
    pushes); destinations stream independently, so one slow or dead
    node never stalls the others.
  - receiver: `push_begin` allocates the object up front from the
    node's arena and REJECTS (not blocks) when the arena can't hold
    it; partial transfers are reaped by age so an aborted sender never
    leaks arena memory.

Failure model: per-destination isolation.  A node dying mid-broadcast
fails that one destination (reported in the result map); the remaining
destinations complete — pinned by tests/test_chaos.py.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from ray_tpu.core.ids import ObjectID


class PushManager:
    """Fan one local object's bytes out to peer node arenas."""

    def __init__(self, runtime, *, chunk_bytes: Optional[int] = None,
                 max_inflight_bytes: int = 64 * 1024 * 1024):
        self._rt = runtime
        self.chunk_bytes = max(
            1 << 20, chunk_bytes or runtime.config.transfer_chunk_bytes)
        self._max_inflight_bytes = max_inflight_bytes

    def broadcast(self, obj_hex: str, size: int,
                  destinations: Sequence[str], *,
                  timeout: float = 600.0) -> Dict[str, str]:
        """Push object bytes to every destination address concurrently.

        Returns {address: "ok" | "have" | "reject: ..." | "error: ..."}.
        The source segment is the local arena copy (it must exist
        here); each destination streams independently on its own node
        connection.
        """
        seg = self._rt.store.attach(ObjectID.from_hex(obj_hex), size)
        results: Dict[str, str] = {}
        lock = threading.Lock()
        # PER-DESTINATION budgets, one global total: a destination that
        # stalls inside a blocking send (partitioned peer with the TCP
        # connection held open) can pin at most ITS OWN permits — the
        # documented "one slow or dead node never stalls the others"
        # invariant would not survive a shared semaphore.
        per_dest = max(1, (self._max_inflight_bytes // self.chunk_bytes)
                       // max(1, len(destinations)))
        budgets = {a: threading.BoundedSemaphore(per_dest)
                   for a in destinations}

        def one(addr: str):
            try:
                results_val = self._push_one(addr, obj_hex, size, seg,
                                             timeout, budgets[addr])
            except Exception as e:  # noqa: BLE001 — per-dest isolation
                results_val = f"error: {type(e).__name__}: {e}"
            with lock:
                results[addr] = results_val

        threads = [threading.Thread(target=one, args=(a,), daemon=True,
                                    name=f"push-{a}")
                   for a in destinations]
        for t in threads:
            t.start()
        # ONE deadline across every join — sequential full-timeout joins
        # would make the worst case len(destinations) * timeout.
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        for a in destinations:
            with lock:
                results.setdefault(a, "error: timeout")
        return results

    def _push_one(self, addr: str, obj_hex: str, size: int, seg,
                  timeout: float, budget) -> str:
        conn = self._rt._node_conn(addr)
        begin = conn.call({"op": "push_begin", "obj": obj_hex,
                           "size": size}, timeout=30.0)
        if begin.get("have"):
            return "have"
        if begin.get("reject"):
            return f"reject: {begin['reject']}"
        off = 0
        deadline = time.monotonic() + timeout
        while off < size:
            n = min(self.chunk_bytes, size - off)
            budget.acquire()
            try:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"broadcast to {addr} timed out")
                # ONE-WAY chunk frames: a synchronous call per chunk
                # costs two scheduler round trips, which on small hosts
                # dominates the transfer (~130 ms per 8 MB measured
                # single-core).  The TCP stream orders chunks, a
                # blocking send applies receiver backpressure, and
                # push_end's byte-count check catches any loss.  The
                # budget bounds bytes handed to the kernel across all
                # destinations — wait=True keeps the accounting honest
                # under rpc coalescing (the budget slot must not be
                # released while the chunk still sits in the send
                # buffer).
                conn.send({"op": "push_chunk", "obj": obj_hex,
                           "offset": off,
                           "data": bytes(seg.buf[off:off + n])},
                          wait=True)
            finally:
                budget.release()
            off += n
        reply = conn.call({"op": "push_end", "obj": obj_hex},
                          timeout=timeout)
        if not (reply or {}).get("ok"):
            return f"error: {(reply or {}).get('error', 'push_end failed')}"
        return "ok"


def broadcast_object(ref, node_ids: Optional[List[str]] = None, *,
                     chunk_bytes: Optional[int] = None,
                     max_inflight_bytes: int = 64 * 1024 * 1024,
                     timeout: float = 600.0) -> Dict[str, str]:
    """Push a shm-resident object to other nodes' arenas ahead of use
    (reference `ObjectManager::Push`): consumers there then read shm
    locally instead of pulling over the wire at first access.

    node_ids: target node ids (default: every alive non-head node that
    doesn't already hold a copy).  Returns {node_id: status}.
    """
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    core = getattr(rt, "core", rt)
    obj_hex = ref.hex() if hasattr(ref, "hex") else str(ref)
    info = core.client.call({"op": "object_info", "obj": obj_hex},
                            timeout=30.0)
    if not info or not info.get("in_shm"):
        raise ValueError(
            f"broadcast_object needs a sealed shm object; {obj_hex} is "
            f"{'inline' if info else 'unknown'}")
    holder = info.get("node", "head")
    if holder != core.store_node:
        # The push source streams from the LOCAL arena; a copy living
        # on another node would fail deep inside store.attach with an
        # arena-internal error — say what is actually wrong instead.
        raise ValueError(
            f"broadcast_object must run where the object lives: "
            f"{obj_hex} is in node {holder!r}'s arena, this process is "
            f"on {core.store_node!r} (fetch it locally first, or "
            "broadcast from that node)")
    nodes = core.client.call({"op": "list_nodes"}, timeout=30.0)
    targets = []
    for n in nodes:
        if not n.get("alive") or n.get("is_head"):
            continue
        if node_ids is not None and n["node_id"] not in node_ids:
            continue
        targets.append((n["node_id"], n["address"]))
    pm = PushManager(core, chunk_bytes=chunk_bytes,
                     max_inflight_bytes=max_inflight_bytes)
    by_addr = pm.broadcast(obj_hex, info["size"],
                           [a for _, a in targets], timeout=timeout)
    return {nid: by_addr.get(a, "error: missing")
            for nid, a in targets}
