"""Worker process: task executor + actor host.

Counterpart of the reference's default_worker.py + the executor half of
CoreWorker (ExecuteTask, core_worker.cc:2906) and the executor-side actor
scheduling queues (transport/actor_scheduling_queue.cc).  Each worker runs:

  - a CoreClient connected to the control server (receives execute_task /
    create_actor_instance pushes),
  - its own rpc.Server so callers submit actor tasks DIRECTLY to this
    process (the reference's peer-to-peer actor transport — GCS is not on
    the actor hot path),
  - an executor: single-slot for pool tasks, FIFO queue (or thread pool for
    max_concurrency > 1) for actor methods.
"""

from __future__ import annotations

import contextvars
import inspect
import os
import queue
import sys
import threading
import time
import traceback
from typing import Any, List, Optional

import cloudpickle

from ray_tpu.core import rpc, serialization
from ray_tpu.core.exceptions import TaskError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.runtime import CoreClient, set_runtime
from ray_tpu.core.task_spec import ActorCreationSpec, KwargsMarker, TaskSpec

# Current task for async actor method bodies: coroutines interleave on
# ONE loop thread, so thread-locals can't carry identity — contextvars
# follow each asyncio task (runtime_context.py reads this).
_current_spec_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_current_task_spec", default=None)

# Cached lazy import (ray_tpu.util eagerly pulls in the runtime; core
# modules import util lazily to stay cycle-free).
_tracing = None


def _get_tracing():
    global _tracing
    if _tracing is None:
        from ray_tpu.util import tracing

        _tracing = tracing
    return _tracing


class WorkerRuntime:
    """The runtime facade inside a worker process (get/put/submit all work,
    so tasks can launch nested tasks and hold actor handles)."""

    def __init__(self, control_addr: str, worker_hex: str, kind: str,
                 env_key: str):
        self.namespace = os.environ.get("RAY_TPU_NAMESPACE", "")
        self._exit_ev = threading.Event()
        from ray_tpu.core.config import get_config

        cfg = get_config()
        self.server = rpc.Server(self._handle_direct,
                                 host=cfg.node_ip_address)
        # Advertised (not bind) address: actor callers on other hosts
        # dial this.
        self.advertised_address = (f"{cfg.advertised_host()}:"
                                   f"{self.server.port}")
        self.core = CoreClient(
            control_addr, worker_hex, kind=kind,
            address=self.advertised_address, env_key=env_key)
        self.core.on_execute_task = self._on_execute_task
        self.core.on_create_actor = self._on_create_actor
        self.core.on_exit = self._on_exit
        self.core.on_reconnect = self._on_reconnect
        self._func_cache: dict[str, Any] = {}
        self._actor_instance: Any = None
        self._actor_is_async = False
        self._actor_hex: str = ""
        self._task_queue: "queue.Queue[TaskSpec]" = queue.Queue()
        self._cancelled_pool: set = set()  # task hexes cancelled while queued
        self._exec_pool: Optional[Any] = None
        self._aio_lock = threading.Lock()
        # Direct-result coalescing (see _push_direct_result).
        self._res_lock = threading.Lock()
        self._res_buf: dict = {}
        self._res_flush_ev = threading.Event()
        threading.Thread(target=self._result_flusher,
                         name="direct-result-flush", daemon=True).start()
        # Per-thread currently-executing spec (runtime_context.py).
        self._cur_tls = threading.local()
        self.is_initialized = True
        set_runtime(self)
        # Apply this pool's runtime env (working_dir/py_modules/env_vars/
        # pip validation — runtime_env/plugin.py) BEFORE reporting online
        # so the first task already sees the prepared environment; a
        # failed setup kills the worker with the error in its .err log
        # (reference: runtime-env agent failure fails the lease).
        renv = self.core.client.call({"op": "get_runtime_env",
                                      "env_key": env_key})
        if renv:
            from ray_tpu.runtime_env.plugin import apply_runtime_env

            try:
                apply_runtime_env(renv, self.core.session_dir,
                                  self.core.client.call)
            except Exception as e:  # noqa: BLE001
                import traceback

                traceback.print_exc()
                # Poison the env server-side so pending/future tasks fail
                # fast instead of respawning this doomed pool forever.
                try:
                    self.core.client.call({
                        "op": "worker_setup_failed", "env_key": env_key,
                        "error": f"{type(e).__name__}: {e}"})
                finally:
                    os._exit(1)
        self.core.client.send({"op": "worker_online"})
        # Low-frequency resource sampler: CPU %, RSS, arena usage and
        # queue depths, shipped as profile_report deltas on the
        # coalescing flusher (runtime._head_frames keeps only the
        # newest sample of a backlogged run).  Head-retunable via the
        # profile_config push; RAY_TPU_PROFILE_SAMPLER=0 disables.
        threading.Thread(target=self._profile_sampler_loop,
                         name="profile-sampler", daemon=True).start()

    # -- per-worker resource profiling ---------------------------------
    def _profile_sampler_loop(self):
        from ray_tpu.core.memory_monitor import system_memory

        cfg = self.core.profile_config
        cfg.setdefault("enabled", os.environ.get(
            "RAY_TPU_PROFILE_SAMPLER", "1").strip().lower()
            not in ("0", "false", "no", "off"))
        try:
            interval = float(os.environ.get(
                "RAY_TPU_PROFILE_SAMPLE_INTERVAL_S", "5"))
        except ValueError:
            interval = 5.0
        cfg.setdefault("interval_s", max(0.05, interval))
        ev = self.core.profile_config_ev
        try:
            ticks = os.sysconf("SC_CLK_TCK") or 100
            page = os.sysconf("SC_PAGE_SIZE") or 4096
        except (ValueError, OSError, AttributeError):
            ticks, page = 100, 4096
        last_cpu_s = last_t = None
        while not self._exit_ev.is_set():
            ev.wait(timeout=float(cfg.get("interval_s", 5.0)))
            ev.clear()
            if self._exit_ev.is_set():
                return
            if not cfg.get("enabled", True):
                last_cpu_s = last_t = None  # stale CPU deltas on resume
                continue
            try:
                sample, last_cpu_s, last_t = self._profile_sample(
                    ticks, page, system_memory, last_cpu_s, last_t)
                self.core._queue_for_flush("profile_report", None, sample)
            except Exception:
                pass  # sampling must never hurt the worker

    def _profile_sample(self, ticks, page, system_memory,
                        last_cpu_s, last_t):
        now = time.monotonic()
        cpu_s = 0.0
        rss = 0
        try:
            with open("/proc/self/stat") as f:
                # utime/stime are fields 14/15; split after the ")" that
                # closes comm (which may itself contain spaces).
                parts = f.read().rsplit(")", 1)[1].split()
            cpu_s = (int(parts[11]) + int(parts[12])) / ticks
        except (OSError, ValueError, IndexError):
            pass
        try:
            with open("/proc/self/statm") as f:
                rss = int(f.read().split()[1]) * page
        except (OSError, ValueError, IndexError):
            pass
        cpu_pct = 0.0
        if last_t is not None and now > last_t:
            cpu_pct = max(
                0.0, 100.0 * (cpu_s - last_cpu_s) / (now - last_t))
        cap, used, nobj, _evicted = self.core.store.stats()
        avail, total = system_memory()
        pool_q = getattr(self, "_pool_queue", None)
        sample = {
            "ts": time.time(), "pid": os.getpid(),
            "worker": self.core.worker_hex,
            "cpu_percent": round(cpu_pct, 2),
            "rss_bytes": rss,
            "mem_available_bytes": avail,
            "mem_total_bytes": total,
            "arena_used_bytes": used,
            "arena_capacity_bytes": cap,
            "arena_objects": nobj,
            "queue_depth": self._task_queue.qsize() + (
                pool_q.qsize() if pool_q is not None else 0),
        }
        # Device-plane piggyback: "device" is None on hosts without an
        # accelerator (JAX_PLATFORMS=cpu emits device: null — the probe
        # never raises and never imports jax itself); recompile counts
        # and the last roofline/MFU window ride along when the process
        # produced them, so the head's history rings grow percentiles
        # for them for free.
        from ray_tpu.util import device_stats

        device_stats.attribute("arena", used)
        sample.update(device_stats.profile_fields())
        return sample, cpu_s, now

    # -- runtime facade (same surface the driver runtime exposes) -------
    def get(self, refs, timeout=None):
        return self.core.get(refs, timeout)

    def put(self, value):
        return self.core.put(value)

    def wait(self, refs, num_returns=1, timeout=None):
        return self.core.wait(refs, num_returns, timeout)

    def submit_task(self, *a, **kw):
        return self.core.submit_task(*a, **kw)

    def create_actor(self, *a, **kw):
        if not kw.get("namespace"):
            kw["namespace"] = self.namespace
        return self.core.create_actor(*a, **kw)

    def submit_actor_task(self, *a, **kw):
        return self.core.submit_actor_task(*a, **kw)

    def kill_actor(self, *a, **kw):
        return self.core.kill_actor(*a, **kw)

    def get_named_actor(self, name: str, namespace: str = ""):
        return self.core.get_named_actor(name, namespace or self.namespace)

    def subscribe_actor(self, *a, **kw):
        return self.core.subscribe_actor(*a, **kw)

    def wait_actor_alive(self, *a, **kw):
        return self.core.wait_actor_alive(*a, **kw)

    def on_ref_deleted(self, object_id: ObjectID):
        self.core.on_ref_deleted(object_id)

    def _local_nm(self):
        """Connection to this node's manager, if any (N8 resource-view
        sync: resource queries answer from the manager's synced view
        without a head round trip)."""
        addr = os.environ.get("RAY_TPU_LOCAL_NM", "")
        if not addr:
            return None
        conn = getattr(self, "_nm_conn", None)
        if conn is not None and not conn._closed:
            return conn
        try:
            conn = rpc.Client(addr, connect_timeout=2.0)
        except Exception:
            return None
        self._nm_conn = conn
        return conn

    def cluster_resources(self):
        nm = self._local_nm()
        if nm is not None:
            try:
                out = nm.call({"op": "cluster_resources"}, timeout=5.0)
                if out:
                    return out
            except Exception:
                pass
        return self.core.client.call({"op": "cluster_resources"})

    def available_resources(self):
        nm = self._local_nm()
        if nm is not None:
            try:
                out = nm.call({"op": "available_resources"}, timeout=5.0)
                if out:
                    return out
            except Exception:
                pass
        return self.core.client.call({"op": "available_resources"})

    def state_list(self, kind: str):
        return self.core.client.call({"op": f"list_{kind}"})

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        out: concurrent.futures.Future = concurrent.futures.Future()
        inner = self.core.object_future(ref.hex())

        def _chain(f):
            try:
                out.set_result(self.core._load_object(ref.hex(), f.result()))
            except BaseException as e:  # noqa: BLE001
                out.set_exception(e)

        inner.add_done_callback(_chain)
        return out

    def kv(self):
        return self.core.client

    # -- direct server (actor task submission path) ---------------------
    def _handle_direct(self, conn, msg):
        op = msg.get("op")
        if op == "actor_task":
            spec = msg["spec"]
            # Owner-direct path: remember which connection the call came
            # in on so the result can be pushed straight back to the
            # submitter (no head involvement) — see _store_returns.
            spec._arrival_conn = conn
            self._task_queue.put(spec)
            return None
        if op == "actor_task_batch":
            for spec in msg["specs"]:
                spec._arrival_conn = conn
                self._task_queue.put(spec)
            return None
        if op == "pool_task":
            # Owner-direct leased task (reference PushNormalTask,
            # direct_task_transport.cc:601): executes on the pool lane;
            # the result rides this connection back.
            spec = msg["spec"]
            spec._arrival_conn = conn
            self._on_execute_task(spec)
            return None
        if op == "pool_task_batch":
            for spec in msg["specs"]:
                spec._arrival_conn = conn
                self._on_execute_task(spec)
            return None
        if op == "cancel_pool_task":
            # Owner-initiated cancel of a dispatched-but-not-started
            # task (reference normal_scheduling_queue CancelTaskIfFound):
            # cancellable only while it still sits in the pool queue.
            task_hex = msg.get("task")
            q = getattr(self, "_pool_queue", None)
            if q is not None:
                # The add must happen under q.mutex: the executor's pop
                # also takes it, so in-queue-while-marked guarantees the
                # drain check sees the hex (no started-anyway race).
                with q.mutex:
                    found = any(
                        s.task_id is not None
                        and s.task_id.hex() == task_hex for s in q.queue)
                    if found:
                        self._cancelled_pool.add(task_hex)
                if found:
                    return {"cancelled": True}
            return {"cancelled": False}
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown direct op {op}")

    # -- execution ------------------------------------------------------
    def _resolve_fn(self, spec: TaskSpec):
        func_id = spec.func_id
        fn = self._func_cache.get(func_id)
        if fn is None:
            blob = spec.func_blob or self.core.fetch_func(func_id)
            if blob is None:
                # The owner's put_func is a one-way send racing the
                # owner-direct task spec (which travels straight to this
                # worker): the blob may still be in flight to the GCS.
                # Brief bounded retry before declaring it missing.
                deadline = time.monotonic() + 5.0
                while blob is None and time.monotonic() < deadline:
                    time.sleep(0.05)
                    blob = self.core.fetch_func(func_id)
            if blob is None:
                raise RuntimeError(f"function {func_id} not found in GCS")
            fn = cloudpickle.loads(blob)
            self._func_cache[func_id] = fn
        return fn

    def _resolve_call(self, spec: TaskSpec):
        """(args, kwargs) for a task spec — the shared preamble of every
        execution path (kwargs ride as a trailing marker arg)."""
        args = self._resolve_args(spec)
        kwargs = {}
        if args and isinstance(args[-1], KwargsMarker):
            kwargs = args.pop().kwargs
        return args, kwargs

    def _resolve_args(self, spec: TaskSpec) -> List[Any]:
        args = []
        for a in spec.args:
            if a.is_ref:
                # Balance this temp ref's __del__ decref with an explicit
                # incref: without it, concurrent tasks borrowing the same
                # arg drove the owner's count negative and the object was
                # freed under other tasks still resolving it.  Rides the
                # coalescing queue (one frame per burst, not per arg);
                # get() below flushes pending sends before subscribing,
                # so the incref still reaches the head first.
                self.core._queue_for_flush("incref", None, a.object_hex)
                ref = ObjectRef(ObjectID.from_hex(a.object_hex))
                args.append(self.core.get([ref])[0])
            else:
                args.append(serialization.deserialize(
                    a.data, ref_deserializer=self.core._on_ref_deser))
        return args

    def _store_error(self, spec: TaskSpec, err: TaskError):
        """Best-effort error store; must not raise (an unstorable error would
        otherwise leave return objects PENDING and the worker wedged)."""
        for oid in spec.return_ids:
            try:
                self.core._store_value(oid, err, is_error=True)
            except BaseException:  # noqa: BLE001  e.g. unpicklable cause
                fallback = TaskError(
                    spec.name or spec.method_name, None,
                    tb=err.traceback_str or str(err))
                fallback.cause = None
                self.core._store_value(oid, fallback, is_error=True)

    def _store_streaming_returns(self, spec: TaskSpec, value: Any,
                                 failed: bool):
        """Drain a generator task: each yield becomes its own object at
        a derived id; the end-of-stream object records the item count
        (core/streaming.py). A mid-stream exception lands in the next
        item slot so iteration surfaces it on get()."""
        from ray_tpu.core.streaming import stream_eos_id, stream_item_id

        count = 0
        if failed:
            self.core._store_value(
                stream_item_id(spec.task_id, 0), value, is_error=True)
            count = 1
        else:
            try:
                for item in value:
                    self.core._store_value(
                        stream_item_id(spec.task_id, count), item)
                    # Streamed items must flow LIVE: puts normally ride
                    # the coalescing queue, but a consumer is already
                    # waiting on this item — and a crash between yields
                    # (or user code calling os._exit) must not lose an
                    # item the generator already produced.  The wire
                    # fence matters for the same reason: bytes buffered
                    # in the rpc sender die with the process too.
                    self.core._flush_direct_sends()
                    self.core.client.flush_sends()
                    count += 1
            except BaseException as e:  # noqa: BLE001
                err = TaskError(spec.name or spec.method_name, e)
                self.core._store_value(
                    stream_item_id(spec.task_id, count), err,
                    is_error=True)
                count += 1
        self.core._store_value(stream_eos_id(spec.task_id), count)
        self.core._flush_direct_sends()
        self.core.client.flush_sends()

    def _store_returns(self, spec: TaskSpec, value: Any, failed: bool):
        if spec.is_streaming:
            self._store_streaming_returns(spec, value, failed)
            return
        if getattr(spec, "direct", False) and \
                self._store_direct_return(spec, value, failed):
            return
        if failed:
            self._store_error(spec, value)
            return
        if spec.num_returns == 1:
            values = [value]
        else:
            try:
                values = list(value)
            except TypeError as e:
                self._store_error(spec, TaskError(spec.name, e))
                return
            if len(values) != spec.num_returns:
                self._store_error(spec, TaskError(
                    spec.name,
                    ValueError(
                        f"task declared {spec.num_returns} returns, got "
                        f"{len(values)}")))
                return
        for oid, v in zip(spec.return_ids, values):
            try:
                self.core._store_value(oid, v)
            except BaseException as e:  # noqa: BLE001 serialization failure
                self._store_error(spec, TaskError(spec.name, e))

    def _store_direct_return(self, spec: TaskSpec, value: Any,
                             failed: bool) -> bool:
        """Push an owner-direct actor result back over the connection the
        task arrived on (reference: direct actor transport replies
        peer-to-peer; the GCS never sees the call).  Returns False to
        fall back to the head path (no arrival conn, e.g. a queued spec
        replayed through an exotic route).  Oversized results go to the
        head store and the owner gets a 'see head' marker instead."""
        conn = getattr(spec, "_arrival_conn", None)
        if conn is None or not spec.return_ids:
            return False
        obj_hex = spec.return_ids[0].hex()
        try:
            ser = self.core._serialize_for_ship(value)
        except BaseException as e:  # noqa: BLE001 unpicklable result
            err = TaskError(spec.name or spec.method_name, e) \
                if not failed else value
            try:
                ser = self.core._serialize_for_ship(err)
            except BaseException:
                fallback = TaskError(
                    spec.name or spec.method_name, None,
                    tb=getattr(err, "traceback_str", None) or str(err))
                fallback.cause = None
                ser = self.core._serialize_for_ship(fallback)
            failed = True
        size = ser.total_bytes
        if size > self.core.config.max_direct_result_bytes:
            # Large result: store via head (shm) and point the owner at
            # it.  For lease-path pool tasks, ship the producing spec as
            # lineage so the head can re-execute on copy loss (the spec
            # never transited the head on submit).
            self.core._store_serialized(
                spec.return_ids[0], ser, is_error=failed,
                lineage_spec=spec if spec.actor_id is None else None)
            # The put rides the coalescing queue; the owner reacts to the
            # push below INSTANTLY (subscribe, or a fire-and-forget
            # __del__ decref) — the head must learn of the object first
            # or that decref lands on nothing and the entry leaks.  The
            # wire fence makes the cross-connection ordering hold under
            # rpc coalescing too (the push travels a different socket).
            self.core._flush_direct_sends()
            self.core.client.flush_sends()
            try:
                conn.push({"op": "direct_result_remote", "obj": obj_hex})
            except Exception:
                pass  # owner gone; the head copy ages out via refcount
            return True
        self._push_direct_result(conn, obj_hex, ser.to_bytes(), failed)
        return True

    def _push_direct_result(self, conn, obj_hex: str, data: bytes,
                            is_error: bool):
        """Coalesce back-to-back results into one direct_result_batch
        push: with more calls already queued, buffer; the buffer flushes
        when the queue drains, at 64 results, or after 1 ms (flusher
        thread) — whichever first.  A lone result pushes immediately, so
        sync callers see no added latency."""
        pool_q = getattr(self, "_pool_queue", None)
        queued = not self._task_queue.empty() or (
            pool_q is not None and not pool_q.empty())
        with self._res_lock:
            buffered = self._res_buf.get(id(conn))
            if buffered is None and not queued:
                buffered = False  # immediate path
            else:
                if buffered is None:
                    buffered = self._res_buf[id(conn)] = (conn, [])
                buffered[1].append((obj_hex, data, is_error))
                n = len(buffered[1])
        if buffered is False:
            try:
                conn.push({"op": "direct_result", "obj": obj_hex,
                           "data": data, "is_error": is_error})
            except Exception:
                pass  # owner disconnected: nobody is waiting
            return
        if n >= 64 or not queued:
            self._flush_direct_results()
        else:
            self._res_flush_ev.set()

    def _flush_direct_results(self):
        with self._res_lock:
            if not self._res_buf:
                return
            bufs, self._res_buf = self._res_buf, {}
        for conn, results in bufs.values():
            try:
                if len(results) == 1:
                    obj_hex, data, is_error = results[0]
                    conn.push({"op": "direct_result", "obj": obj_hex,
                               "data": data, "is_error": is_error})
                else:
                    conn.push({"op": "direct_result_batch",
                               "results": results})
            except Exception:
                pass  # owner disconnected

    def _result_flusher(self):
        """Bounds the buffering delay: a burst followed by a slow task
        must not park finished results behind it."""
        while not self._exit_ev.is_set():
            self._res_flush_ev.wait()
            self._res_flush_ev.clear()
            time.sleep(0.001)
            self._flush_direct_results()

    def _finish(self, spec: TaskSpec, failed: bool,
                puts: Optional[List[dict]] = None):
        if spec.actor_id is None:
            if getattr(spec, "direct", False) and \
                    getattr(spec, "_arrival_conn", None) is not None:
                # Leased task (owner-direct): no head slot to return —
                # the lease holds the resources until the owner releases
                # it.  Only the borrow decrefs (coalesced) and a batched
                # task event for observability go to the head
                # (reference: TaskEventBuffer flushes execution events
                # off the hot path, task_event_buffer.h:206).
                for obj_hex in spec.borrows:
                    self.core._queue_for_flush("decref", None, obj_hex)
                self._buffer_task_event(spec, failed)
                if getattr(self, "_announce_pending", False):
                    # Deferred post-head-restart announce (see
                    # _on_reconnect): without it this worker would stay
                    # 'starting' on the restarted head forever.
                    pool_q = getattr(self, "_pool_queue", None)
                    if pool_q is None or pool_q.empty():
                        self._announce_pending = False
                        try:
                            self.core.client.send({"op": "worker_online"})
                        except Exception:
                            pass
                return
            # One combined control message: result puts + borrow decrefs
            # + completion (was 1 put per return + 1 decref per borrow +
            # 1 done = the control plane's hottest path).
            msg = {
                "op": "task_done", "task_id": spec.task_id.hex(),
                "failed": failed, "puts": puts or [],
                "decrefs": list(spec.borrows)}
            tr = getattr(spec, "_trace", None)
            if tr is not None:
                msg["trace"] = tr
            self.core.client.send(msg)
            self._announce_pending = False  # task_done re-binds state
        else:
            # Actor-method borrows: ride the coalescing queue so a burst
            # of completions releases refs in delta vectors, not one
            # frame per borrowed arg.
            for obj_hex in spec.borrows:
                self.core._queue_for_flush("decref", None, obj_hex)

    def _buffer_task_event(self, spec: TaskSpec, failed: bool,
                           state: str = ""):
        """Queue a compact task-lifecycle delta; it rides the core
        client's coalescing flusher (runtime.py _queue_for_flush /
        _head_frames), where a run of events collapses into one
        task_events frame and same-task deltas within a flush window
        merge — so the state API / timeline / OOM victim policy still
        see lease-path tasks the head never scheduled, at far fewer
        frames than tasks (reference GcsTaskManager events +
        TaskEventBuffer, task_event_buffer.h:206)."""
        state = state or ("FAILED" if failed else "FINISHED")
        ev = {
            "task_id": spec.task_id.hex(),
            "name": spec.name or spec.func_id[:8],
            "owner": spec.owner,
            "state": state,
            "retries_left": max(0, spec.max_retries - spec.retry_count),
            "retry_count": spec.retry_count,
        }
        received = getattr(spec, "_received_at", 0.0)
        if received:
            ev["received"] = received
        if state != "RECEIVED":
            ev["start"] = getattr(spec, "_exec_started", 0.0)
            if state != "RUNNING":
                ev["end"] = time.time()
                if ev["start"]:
                    ev["duration"] = ev["end"] - ev["start"]
        tr = getattr(spec, "_trace", None)
        if tr is not None:
            # One compact key, not trace_id/span_id/parent_span_id: the
            # key names alone would add ~40 bytes to every event frame.
            ev["trace"] = tr
        self.core._queue_for_flush("task_event", None, ev)

    def _execute(self, spec: TaskSpec, target_fn=None):
        failed = False
        self._executing = True
        self._cur_tls.spec = spec
        spec._exec_started = time.time()
        # Restore the submitter's trace context (util/tracing.py): the
        # execution span parents everything this task does — nested
        # submissions carry ITS span id, stitching the driver→worker→
        # nested-task chain under one trace_id.
        _ttok = _span_id = None
        tctx = getattr(spec, "trace_ctx", None)
        if tctx:
            _ttok, _span_id = _get_tracing().begin_task_span(tctx)
            spec._trace = (tctx[0], _span_id, tctx[1])
        if spec.actor_id is None and getattr(spec, "direct", False) and \
                getattr(spec, "_arrival_conn", None) is not None:
            # Leased task: tell the head it is RUNNING here (batched) so
            # the state API and the OOM victim policy see it.
            self._buffer_task_event(spec, failed=False, state="RUNNING")
        # Pool (non-actor, non-streaming) tasks batch their result puts
        # into the task_done message; streaming items must flow live.
        # Leased (owner-direct) tasks send no task_done at all, so their
        # (rare, oversized-result) puts must flow immediately.
        batch_puts = (spec.actor_id is None and not spec.is_streaming
                      and not (getattr(spec, "direct", False)
                               and getattr(spec, "_arrival_conn", None)
                               is not None))
        try:
            args, kwargs = self._resolve_call(spec)
            fn = target_fn if target_fn is not None else self._resolve_fn(spec)
            value = fn(*args, **kwargs)
            if inspect.iscoroutine(value):
                # Async actor method (reference: asyncio actors run via
                # fibers, transport/fiber.h): await it on the actor's
                # event loop. Each exec thread blocks on ITS call while
                # the loop overlaps awaits across threads, so
                # max_concurrency requests make progress concurrently.
                import asyncio

                value = asyncio.run_coroutine_threadsafe(
                    value, self._actor_event_loop()).result()
        except BaseException as e:  # noqa: BLE001
            failed = True
            value = TaskError(spec.name or spec.method_name, e)
            traceback.print_exc()
        puts: Optional[List[dict]] = None
        try:
            if batch_puts:
                self.core.begin_put_batch()
            self._store_returns(spec, value, failed)
        except BaseException:  # noqa: BLE001
            failed = True
            traceback.print_exc()
        finally:
            if batch_puts:
                puts = self.core.take_put_batch()
            self._cur_tls.spec = None
            self._executing = False
            # Always release resources/borrows, even if storing returns
            # blew up — a wedged-busy worker starves the whole pool.
            self._finish(spec, failed, puts)
            if _ttok is not None:
                _get_tracing().end_task_span(
                    _ttok,
                    f"task:{spec.name or spec.method_name or spec.func_id[:8]}",
                    spec._exec_started, time.time(), tctx, _span_id)
        return failed

    @property
    def _current_task_spec(self):
        ctx_spec = _current_spec_ctx.get()
        if ctx_spec is not None:
            return ctx_spec
        return getattr(self._cur_tls, "spec", None)

    def _on_execute_task(self, spec: TaskSpec):
        # pool tasks: one at a time on a PERSISTENT executor thread (a
        # thread spawn per task costs ~100 us — the dominant per-task
        # overhead at small-task rates); the rpc receive thread stays
        # responsive because it only enqueues.
        spec._received_at = time.time()
        if getattr(spec, "direct", False) and \
                getattr(spec, "_arrival_conn", None) is not None:
            # Lease-path task: the head never saw the submission, so
            # the arrival delta is its first sighting (it merges with
            # RUNNING/FINISHED if the task drains fast).
            self._buffer_task_event(spec, failed=False, state="RECEIVED")
        q = getattr(self, "_pool_queue", None)
        if q is None:
            with self._aio_lock:
                q = getattr(self, "_pool_queue", None)
                if q is None:
                    q = queue.Queue()
                    threading.Thread(target=self._pool_exec_loop,
                                     args=(q,), name="task-exec",
                                     daemon=True).start()
                    self._pool_queue = q
        q.put(spec)

    def _pool_exec_loop(self, q: "queue.Queue[TaskSpec]"):
        while not self._exit_ev.is_set():
            try:
                spec = q.get(timeout=0.2)
            except queue.Empty:
                continue
            th = spec.task_id.hex() if spec.task_id is not None else None
            if th is not None and th in self._cancelled_pool:
                # Owner cancelled it while queued: release borrows and
                # report the terminal event, never run the body.  The
                # owner already failed its future with
                # TaskCancelledError (cancel_ref).
                self._cancelled_pool.discard(th)
                self._finish(spec, failed=True)
                continue
            self._execute(spec)

    # -- actor hosting --------------------------------------------------
    def _on_create_actor(self, spec: ActorCreationSpec):
        threading.Thread(
            target=self._create_actor_instance, args=(spec,),
            name="actor-init", daemon=True).start()

    def _create_actor_instance(self, spec: ActorCreationSpec):
        try:
            blob = spec.class_blob or self.core.fetch_func(spec.class_id)
            cls = cloudpickle.loads(blob)
            fake_task = TaskSpec(
                task_id=None, func_id="", func_blob=None, args=spec.args,
                num_returns=0, return_ids=[], resources={},
                borrows=[])
            args, kwargs = self._resolve_call(fake_task)
            self._actor_instance = cls(*args, **kwargs)
            self._actor_hex = spec.actor_id.hex()
            # Async actors serialize ALL method bodies on one event loop
            # (see _actor_loop); detected once here.
            self._actor_is_async = any(
                inspect.iscoroutinefunction(m)
                for _, m in inspect.getmembers(
                    type(self._actor_instance),
                    predicate=inspect.isfunction))
            # Named concurrency groups: one bounded executor pool per
            # group (reference concurrency_group_manager.cc); methods
            # annotated @ray_tpu.method(concurrency_group=...) run
            # there, overlapping with the default lane while staying
            # FIFO within their group.
            groups = getattr(spec, "concurrency_groups", None) or {}
            if groups:
                from concurrent.futures import ThreadPoolExecutor

                self._group_pools = {
                    gname: ThreadPoolExecutor(
                        max_workers=max(1, int(size)),
                        thread_name_prefix=f"actor-cg-{gname}")
                    for gname, size in groups.items()}
                # With groups on, the queue thread is a pure
                # dispatcher: un-grouped methods run on a default pool
                # (size max_concurrency) so a long default-lane call
                # never blocks dispatch into the other lanes.
                self._group_pools["_default"] = ThreadPoolExecutor(
                    max_workers=max(1, spec.max_concurrency),
                    thread_name_prefix="actor-cg-default")
            # With groups, exactly ONE dispatcher thread feeds the pools
            # (multiple dispatchers would race task_queue.get -> submit
            # and break FIFO within a group); concurrency comes from the
            # pools themselves.  Without groups, the queue threads ARE
            # the executors.
            n = 1 if groups else max(1, spec.max_concurrency)
            for _ in range(n):
                threading.Thread(target=self._actor_loop, name="actor-exec",
                                 daemon=True).start()
            self.core.client.send({
                "op": "actor_ready", "actor": spec.actor_id.hex(),
                "address": self.advertised_address})
        except BaseException as e:  # noqa: BLE001
            traceback.print_exc()
            self.core.client.send({
                "op": "actor_creation_failed", "actor": spec.actor_id.hex(),
                "reason": "".join(traceback.format_exception(e))[-2000:]})

    def _actor_loop(self):
        while not self._exit_ev.is_set():
            try:
                spec = self._task_queue.get(timeout=0.2)
            except queue.Empty:
                continue
            method_name = spec.method_name
            if method_name == "__ray_terminate__":
                self._store_returns(spec, None, failed=False)
                self._on_exit()
                return
            if method_name == "__ray_tpu_compiled_loop__":
                # compiled-DAG pin: run the resident stage loop (blocks this
                # actor thread until the DAG is torn down)
                from ray_tpu.dag.compiled_dag import run_actor_loop

                inst = self._actor_instance
                self._execute(
                    spec,
                    target_fn=lambda desc: run_actor_loop(inst, desc))
                continue
            try:
                method = getattr(self._actor_instance, method_name)
            except AttributeError as e:
                self._store_returns(
                    spec, TaskError(method_name, e), failed=True)
                self._finish(spec, failed=True)
                continue
            if self._actor_is_async:
                # Async actor: EVERY method body runs on the actor's
                # event loop (sync ones wrapped in a trivial coroutine),
                # so no two bodies ever run in parallel — the reference's
                # asyncio-actor serialization — while awaits overlap.
                # The queue thread moves on immediately; no parked OS
                # thread per in-flight call.
                self._execute_async_actor_task(spec, method)
            else:
                pools = getattr(self, "_group_pools", None)
                if pools:
                    group = getattr(method, "__concurrency_group__", None)
                    if group is not None and group not in pools:
                        # An undeclared group silently landing in the
                        # default lane would quietly drop the isolation
                        # the caller asked for — fail the call instead.
                        self._store_returns(
                            spec, TaskError(method_name, ValueError(
                                f"method {method_name!r} names "
                                f"concurrency group {group!r}, which "
                                "this actor does not declare")),
                            failed=True)
                        self._finish(spec, failed=True)
                        continue
                    pool = pools.get(group) or pools["_default"]
                    # Grouped dispatch: lanes overlap; FIFO within a
                    # lane; the single dispatcher thread moves on.
                    pool.submit(self._execute, spec, method)
                else:
                    self._execute(spec, target_fn=method)

    def _execute_async_actor_task(self, spec: TaskSpec, method):
        import asyncio

        try:
            args, kwargs = self._resolve_call(spec)

            async def _body():
                _current_spec_ctx.set(spec)
                tctx = getattr(spec, "trace_ctx", None)
                if tctx:
                    # Each asyncio task runs in its own contextvars copy:
                    # install-without-reset is safe and nested submissions
                    # from the body parent to this execution span.
                    sid = _get_tracing().set_task_ctx(tctx)
                    spec._trace = (tctx[0], sid, tctx[1])
                if inspect.iscoroutinefunction(method):
                    return await method(*args, **kwargs)
                # Sync method of an async actor: run its body ON the
                # loop so it serializes with async bodies.
                return method(*args, **kwargs)

            coro = _body()
        except BaseException as e:  # noqa: BLE001
            traceback.print_exc()
            self._store_returns(
                spec, TaskError(spec.method_name, e), failed=True)
            self._finish(spec, failed=True)
            return
        fut = asyncio.run_coroutine_threadsafe(
            coro, self._actor_event_loop())

        def _store(f):
            failed = False
            try:
                value = f.result()
            except BaseException as e:  # noqa: BLE001
                failed = True
                value = TaskError(spec.method_name, e)
                traceback.print_exc()
            try:
                self._store_returns(spec, value, failed)
            except BaseException:  # noqa: BLE001
                failed = True
                traceback.print_exc()
            finally:
                self._finish(spec, failed)

        # Completion (serialization + shm write + control sends) runs on
        # a dedicated thread, NOT the loop thread — a multi-MB result
        # must not stall every other in-flight await on this actor.
        fut.add_done_callback(
            lambda f: self._async_completions().submit(_store, f))

    def _actor_event_loop(self):
        """Lazily start this actor's asyncio loop thread."""
        loop = getattr(self, "_aio_loop", None)
        if loop is None:
            import asyncio

            with self._aio_lock:
                loop = getattr(self, "_aio_loop", None)
                if loop is None:
                    loop = asyncio.new_event_loop()
                    threading.Thread(target=loop.run_forever,
                                     name="actor-asyncio",
                                     daemon=True).start()
                    self._aio_loop = loop
        return loop

    def _async_completions(self):
        """Single-thread executor storing async task results in
        completion order (off the loop thread)."""
        pool = getattr(self, "_aio_done_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._aio_lock:
                pool = getattr(self, "_aio_done_pool", None)
                if pool is None:
                    pool = ThreadPoolExecutor(
                        max_workers=1, thread_name_prefix="actor-aio-done")
                    self._aio_done_pool = pool
        return pool

    # -- lifecycle ------------------------------------------------------
    def _on_reconnect(self):
        """Control plane came back (head restart): re-announce so the
        restored registry can rebind this worker (reference: raylet
        re-registration after NotifyGCSRestart)."""
        try:
            if self._actor_hex:
                self.core.client.send({
                    "op": "actor_ready", "actor": self._actor_hex,
                    "address": self.advertised_address})
            elif not getattr(self, "_executing", False):
                # Mid-task workers must NOT report online: the restarted
                # head would mark them idle and double-book a second
                # concurrent task; the in-flight task's task_done flips
                # them idle when it actually finishes.
                self.core.client.send({"op": "worker_online"})
            else:
                # Leased tasks send no task_done, so nothing would ever
                # flip this worker out of 'starting' on the restarted
                # head — announce when the current work drains
                # (_finish direct branch).
                self._announce_pending = True
        except Exception:
            pass

    def _on_exit(self):
        self._exit_ev.set()

    def run_forever(self):
        self._exit_ev.wait()
        try:
            self.server.stop()
            self.core.close()
        finally:
            os._exit(0)


def main():
    import faulthandler

    faulthandler.enable()  # native-crash stacks land in the worker .err log
    from ray_tpu.core import knobs

    knobs.apply_interpreter_tuning()
    from ray_tpu.core.logging_config import apply_from_env

    apply_from_env()  # session LoggingConfig (TEXT/JSON), if the driver set one
    control_addr = os.environ["RAY_TPU_CONTROL_ADDR"]
    worker_hex = os.environ["RAY_TPU_WORKER_ID"]
    kind = os.environ.get("RAY_TPU_WORKER_KIND", "pool")
    env_key = os.environ.get("RAY_TPU_ENV_KEY", "")
    rt = WorkerRuntime(control_addr, worker_hex, kind=kind, env_key=env_key)
    rt.run_forever()


if __name__ == "__main__":
    main()
