"""Host memory monitor + OOM worker-killing policy.

Counterparts: src/ray/common/memory_monitor.h:52 (periodic usage
sampling against a threshold) and the raylet's worker-killing policies
(src/ray/raylet/worker_killing_policy*.cc — kill retriable tasks first,
newest first, so long-running work survives and the killed task retries).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Tuple


def system_memory() -> Tuple[int, int]:
    """(available_bytes, total_bytes) from /proc/meminfo; respects a
    cgroup v2 limit when one is set (containerized nodes)."""
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        return (0, 0)
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw != "max":
            limit = int(raw)
            with open("/sys/fs/cgroup/memory.current") as f:
                used = int(f.read().strip())
            # memory.current counts reclaimable page cache; subtract
            # inactive_file so streaming IO (including our own spill
            # writes) doesn't read as pressure — matching the host
            # path's MemAvailable semantics (and the reference's
            # memory_monitor.cc, which does the same).
            try:
                with open("/sys/fs/cgroup/memory.stat") as f:
                    for line in f:
                        if line.startswith("inactive_file "):
                            used = max(0, used - int(line.split()[1]))
                            break
            except (OSError, ValueError):
                pass
            if limit < total:
                return (max(limit - used, 0), limit)
    except (OSError, ValueError):
        pass
    return (avail, total)


def memory_usage_fraction() -> float:
    avail, total = system_memory()
    if not total:
        return 0.0
    return 1.0 - avail / total


class MemoryMonitor:
    """Samples usage every `interval_s`; calls `on_high(fraction)` while
    above `threshold`."""

    def __init__(self, threshold: float = 0.95, interval_s: float = 1.0,
                 on_high: Optional[Callable[[float], None]] = None,
                 usage_fn: Callable[[], float] = memory_usage_fraction):
        self.threshold = threshold
        self.interval_s = interval_s
        self.on_high = on_high
        self.usage_fn = usage_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="memory-monitor")

    def start(self) -> "MemoryMonitor":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                frac = self.usage_fn()
            except Exception:
                continue
            if frac >= self.threshold and self.on_high is not None:
                try:
                    self.on_high(frac)
                except Exception:
                    pass


def pick_worker_to_kill(candidates: List[dict],
                        allow_nonretriable: bool = False
                        ) -> Optional[dict]:
    """Retriable-FIFO policy (worker_killing_policy.cc): kill the most
    recently started RETRIABLE task's worker (LIFO — oldest work is most
    expensive to lose). Candidates: dicts with `retriable` (bool) and
    `started_at` (float).

    Returns None when nothing is safe to kill. Only with
    `allow_nonretriable=True` (last-resort pressure, where the
    alternative is the kernel OOM-killing the whole node) will a
    non-retriable task's worker be chosen."""
    retriable = [c for c in candidates if c.get("retriable")]
    pool = retriable or (candidates if allow_nonretriable else [])
    if not pool:
        return None
    return max(pool, key=lambda c: c.get("started_at") or 0.0)
