"""Worker zygote: fork-server that spawns workers from a warm template.

Starting a worker as a fresh interpreter pays the full import chain every
time (python startup + ray_tpu.core.worker + numpy + jax).  The reference
amortizes this with WorkerPool prestart (worker_pool.h:159 keeps idle
workers around before they are needed); a zygote goes further: ONE
template process per (head | node manager) imports everything once, then
every subsequent worker is an os.fork() of that warm image — milliseconds
instead of seconds, which is what makes thousand-actor populations and
worker-churn tests cheap on small hosts.

Safety model: the zygote binds its unix socket, imports the worker stack,
and only then serves requests from a SINGLE-THREADED loop — at fork time
no other thread can hold a lock in the child.  JAX is imported (cheap to
verify: its import spawns no threads) but no backend is ever initialized
in the template, so XLA client threads/devices are created per-child,
after the fork, honoring each worker's own XLA_FLAGS.

Workers whose spawn genuinely needs a fresh exec — container runtime
envs (chroot wrapper) and TPU-visible workers (sitecustomize path) —
keep the subprocess.Popen path in node_manager.spawn_worker_process.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, Optional

_LEN = struct.Struct("<I")


def _send_msg(sock: socket.socket, obj) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


class _Desync(OSError):
    """Partial frame (EOF or timeout mid-message): the stream position is
    unknowable — the connection must be dropped, never re-read."""


def _recv_msg(sock: socket.socket):
    """Read one frame.  None = clean EOF between frames; socket.timeout
    between frames propagates (idle); a timeout or EOF MID-frame raises
    _Desync so callers close instead of parsing from a torn position."""
    hdr = _recv_exact(sock, _LEN.size, started=False)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    body = _recv_exact(sock, n, started=True)
    return json.loads(body)


def _recv_exact(sock: socket.socket, n: int, *,
                started: bool) -> Optional[bytes]:
    """started=False: clean EOF returns None, zero-byte timeout
    propagates socket.timeout (idle).  Any partial read ending in EOF or
    timeout raises _Desync."""
    buf = b""
    while len(buf) < n:
        try:
            part = sock.recv(n - len(buf))
        except socket.timeout:
            if buf or started:
                raise _Desync("timeout mid-frame")
            raise
        if not part:
            if buf or started:
                raise _Desync("EOF mid-frame")
            return None
        buf += part
    return buf


# ---------------------------------------------------------------------------
# Server side (the template process)
# ---------------------------------------------------------------------------


class _ZygoteServer:
    def __init__(self, sock_path: str):
        self.sock_path = sock_path
        self.listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(sock_path)
        except FileNotFoundError:
            pass
        self.listener.bind(sock_path)
        self.listener.listen(4)
        self.children: Dict[int, str] = {}  # pid -> spawn nonce ("" if none)
        self.exited: Dict[int, int] = {}  # pid -> exit code (drained by poll)
        self.parent_pid = os.getppid()
        self._jax_warmed = False
        self._fork_unsafe = False

    def warm(self) -> None:
        """Import the worker stack (fast — a few hundred ms).  Runs after
        bind/listen so the owner's connect() never races it.  The heavier
        jax import stays DEFERRED to idle loop ticks (_warm_jax): at
        cluster boot every template (head + each node manager) would
        otherwise burn seconds of CPU importing jax concurrently with
        worker spawns — on small hosts that starves remote nodes of
        their first workers and measurably skews scheduling.  A
        spawn/poll colliding with the deferred import times out
        client-side and falls back to Popen; the owner's stale-nonce
        reap cleans up if the buffered spawn executes later, and the
        prewarm ping's long reconnect timeout (ZygoteHandle._ensure)
        keeps those collisions from counting toward the disable
        threshold."""
        import ray_tpu.core.worker  # noqa: F401  (the whole point)

        try:
            import numpy  # noqa: F401
        except Exception:
            pass
        self._check_fork_safe()

    def _check_fork_safe(self) -> None:
        if threading.active_count() > 1:
            # A pre-imported module started a thread: forking now could
            # inherit a lock held by it.  Refuse spawns; the owner falls
            # back to Popen spawns.
            print("zygote: import started extra threads "
                  f"({[t.name for t in threading.enumerate()]})",
                  file=sys.stderr, flush=True)
            self._fork_unsafe = True

    def _warm_jax(self) -> None:
        """Import jax on an idle tick — import only, never backend init:
        XLA client/device threads must be created per-child, post-fork,
        under each worker's own XLA_FLAGS/platform env."""
        self._jax_warmed = True
        try:
            import jax  # noqa: F401
        except Exception:
            pass
        self._check_fork_safe()

    def serve_forever(self) -> None:
        self.listener.settimeout(0.5)
        conn = None
        while True:
            self._reap()
            if os.getppid() != self.parent_pid:
                break  # owner died; workers are independent sessions
            if conn is None:
                try:
                    conn, _ = self.listener.accept()
                except socket.timeout:
                    if not self._jax_warmed:
                        self._warm_jax()
                    continue
                conn.settimeout(0.5)
            try:
                req = _recv_msg(conn)
            except socket.timeout:
                if not self._jax_warmed:
                    self._warm_jax()
                continue
            except OSError:
                req = None
            if req is None:
                conn.close()
                conn = None  # owner reconnect allowed
                continue
            try:
                reply = self._handle(req, conn)
            except SystemExit:
                raise
            except Exception as e:  # noqa: BLE001 — report, keep serving
                reply = {"error": f"{type(e).__name__}: {e}"}
            if reply is not None:
                try:
                    _send_msg(conn, reply)
                except OSError:
                    # The owner closed this connection (e.g. a client-side
                    # timeout while this request sat in the socket buffer).
                    # If the request we just served was a spawn, the owner
                    # never learned the pid and has already fallen back to
                    # a Popen spawn under the SAME worker id — kill the
                    # orphan fork before two processes register as one
                    # worker.
                    if req.get("op") == "spawn" and "pid" in reply:
                        try:
                            os.kill(reply["pid"], signal.SIGKILL)
                        except (ProcessLookupError, PermissionError):
                            pass
                        self.children.pop(reply["pid"], None)
                    conn.close()
                    conn = None

    def _reap(self) -> None:
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return
            self.children.pop(pid, None)
            self.exited[pid] = (os.waitstatus_to_exitcode(status)
                                if hasattr(os, "waitstatus_to_exitcode")
                                else status)
            if len(self.exited) > 8192:  # bound the history
                for old in list(self.exited)[:4096]:
                    del self.exited[old]

    def _handle(self, req: dict, conn: socket.socket):
        op = req.get("op")
        if op == "spawn":
            if self._fork_unsafe:
                return {"error": "template has extra threads; fork unsafe"}
            pid = os.fork()
            if pid == 0:
                self._child(req, conn)  # never returns
            self.children[pid] = req.get("nonce", "")
            # The kernel may hand a new fork a previously-recorded pid;
            # a stale exit record would make the owner declare the new
            # worker dead on its first poll.
            self.exited.pop(pid, None)
            return {"pid": pid, "nonce": req.get("nonce", "")}
        if op == "poll_all":
            self._reap()
            out = {"alive": list(self.children), "exited": self.exited}
            self.exited = {}
            return out
        if op == "kill":
            try:
                os.kill(req["pid"], req.get("sig", signal.SIGKILL))
                return {"ok": True}
            except ProcessLookupError:
                return {"ok": False}
        if op == "reap_stale":
            # The owner timed out waiting for these spawns' replies and
            # fell back to Popen: if any of them executed anyway, the fork
            # is a ghost worker sharing the fallback's worker id — kill it.
            stale = set(req.get("nonces", ()))
            killed = []
            for pid, nonce in list(self.children.items()):
                if nonce and nonce in stale:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
                    self.children.pop(pid, None)
                    killed.append(pid)
            return {"ok": True, "killed": killed}
        if op == "ping":
            return {"ok": True, "pid": os.getpid()}
        if op == "shutdown":
            for pid in list(self.children):
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            try:
                _send_msg(conn, {"ok": True})
            except OSError:
                pass
            raise SystemExit(0)
        return {"error": f"unknown op {op!r}"}

    def _child(self, req: dict, conn: socket.socket) -> None:
        """Forked child: become the worker process."""
        try:
            os.setsid()
            self.listener.close()
            conn.close()
            log_base = req["log_base"]
            out = open(log_base + ".out", "ab", buffering=0)
            err = open(log_base + ".err", "ab", buffering=0)
            os.dup2(out.fileno(), 1)
            os.dup2(err.fileno(), 2)
            for s in (signal.SIGTERM, signal.SIGINT, signal.SIGCHLD):
                signal.signal(s, signal.SIG_DFL)
            try:  # name the fork for ps/top (cmdline still reads zygote)
                import ctypes

                libc = ctypes.CDLL(None, use_errno=True)
                libc.prctl(15, b"rt-worker", 0, 0, 0)  # PR_SET_NAME
            except Exception:
                pass
            env = req["env"]
            os.environ.clear()
            os.environ.update(env)
            # PYTHONPATH is normally consumed at interpreter start; a
            # forked worker applies additions (runtime-env py_modules /
            # user paths) by hand.
            for p in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
                if p and p not in sys.path:
                    sys.path.insert(0, p)
            cwd = req.get("cwd")
            if cwd:
                try:
                    os.chdir(cwd)
                except OSError:
                    pass
            import random

            random.seed()  # forked children must not share RNG streams
            try:
                import numpy as _np

                _np.random.seed()
            except Exception:
                pass
            from ray_tpu.core.config import reset_config

            reset_config()  # env differs from the template's
            from ray_tpu.core import worker

            worker.main()
            os._exit(0)
        except SystemExit as e:
            os._exit(int(e.code or 0))
        except BaseException:  # noqa: BLE001 — last-resort child report
            import traceback

            traceback.print_exc()
            os._exit(1)


def main() -> None:
    sock_path = None
    args = sys.argv[1:]
    for i, a in enumerate(args):
        if a == "--socket":
            sock_path = args[i + 1]
    if not sock_path:
        print("usage: zygote --socket PATH", file=sys.stderr)
        raise SystemExit(2)
    srv = _ZygoteServer(sock_path)
    srv.warm()
    try:
        srv.serve_forever()
    finally:
        try:
            os.unlink(sock_path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Owner side (head process / node manager daemon)
# ---------------------------------------------------------------------------


class ZygoteProc:
    """Popen-alike for a zygote-forked worker (pid/poll/terminate/kill)."""

    __slots__ = ("pid", "returncode", "_handle")

    def __init__(self, handle: "ZygoteHandle", pid: int):
        self._handle = handle
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is None:
            try:  # reaped-and-gone is detectable without any template IPC
                os.kill(self.pid, 0)
            except ProcessLookupError:
                self.returncode = self._handle.exit_code(self.pid)
                return self.returncode
            except PermissionError:
                pass
            self.returncode = self._handle.status(self.pid)
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while self.poll() is None:
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired("zygote-worker", timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]

    def terminate(self) -> None:
        self._handle.kill(self.pid, signal.SIGTERM)

    def kill(self) -> None:
        self._handle.kill(self.pid, signal.SIGKILL)

    def __repr__(self) -> str:
        return f"<ZygoteProc pid={self.pid} returncode={self.returncode}>"


class ZygoteHandle:
    """Lazily starts and talks to this process's zygote template."""

    _POLL_CACHE_S = 0.3

    def __init__(self):
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._conn: Optional[socket.socket] = None
        self._sock_path: Optional[str] = None
        self._alive: set = set()
        self._exited: Dict[int, int] = {}
        self._polled_at = 0.0
        self._broken = False
        # Until the template answers a ping, spawn() raises and callers
        # use the Popen path — a cold/contended template must never add
        # latency to a worker the scheduler is already waiting on.
        self._ready = False
        self._warming = False
        self._failures = 0
        self._disabled = False
        # Nonces of spawn requests whose reply we never saw (client-side
        # timeout): the template may still execute them later, forking a
        # ghost worker under a worker id we have already re-used for a
        # Popen fallback.  Flushed as a reap_stale op before the next
        # request so such forks are detected and killed.  Ordered so the
        # overflow bound evicts the OLDEST nonce, never a pending one.
        self._stale_nonces: Dict[str, None] = {}

    def prewarm(self) -> None:
        """Kick off template start + connect on a daemon thread (idempotent,
        never blocks).  Call at head/node-manager startup so warmup hides
        inside cluster boot."""
        with self._lock:
            if self._ready or self._warming or self._disabled:
                return
            self._warming = True

        def _bg():
            # The template's deferred jax import (_warm_jax) can block
            # its serve loop for seconds; a ping colliding with it times
            # out at the normal 5 s.  That is the WARMUP WINDOW, not a
            # broken template: retry with short attempts (each holds
            # self._lock for at most the 5 s socket timeout, so
            # foreground spawn/status callers stay fail-fast) until a
            # deadline, and only count a disable strike when the whole
            # window expires.
            deadline = time.time() + 120.0
            try:
                while True:
                    try:
                        self._request({"op": "ping"}, start=True)
                        self._ready = True
                        self._failures = 0
                        return
                    except Exception:
                        if time.time() >= deadline:
                            self._failures += 1
                            if self._failures >= 3:
                                # Broken environment: stay on Popen.
                                self._disabled = True
                            return
                        time.sleep(1.0)
            finally:
                self._warming = False

        threading.Thread(target=_bg, daemon=True,
                         name="zygote-warmup").start()

    # -- lifecycle ---------------------------------------------------------

    def _ensure(self, start: bool) -> None:
        """Lock held.  Connect (and with start=True, launch) the template.

        Only prewarm's background thread passes start=True: every
        foreground caller — spawn under the head's scheduler lock,
        poll/kill under sweep locks — must never pay template startup
        (up to 120 s of warm imports); they fail fast and fall back."""
        alive = self._proc is not None and self._proc.poll() is None
        if self._conn is not None and alive and not self._broken:
            return
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None
        if alive and self._sock_path:
            # Template still running, only the socket hiccuped: the
            # server loops back to accept(), so reconnect instead of
            # abandoning the warm template for the session.
            try:
                conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                conn.settimeout(5.0)  # template is warm already
                conn.connect(self._sock_path)
                self._conn = conn
                self._broken = False
                return
            except OSError:
                try:
                    self._proc.kill()
                except OSError:
                    pass
                self._proc = None
        if not start:
            self._ready = False  # route spawns to Popen; prewarm restarts
            raise RuntimeError("zygote template not running")
        from ray_tpu.core.node_manager import cpu_worker_env

        self._sock_path = os.path.join(
            tempfile.gettempdir(), f"rtz-{os.getpid()}-{os.urandom(4).hex()}")
        env = cpu_worker_env(dict(os.environ))
        log = open(os.path.join(tempfile.gettempdir(),
                                f"rtz-{os.getpid()}.log"), "ab")
        self._proc = subprocess.Popen(
            [sys.executable, "-S", "-m", "ray_tpu.core.zygote",
             "--socket", self._sock_path],
            env=env, stdin=subprocess.DEVNULL, stdout=log, stderr=log)
        deadline = time.time() + 30.0
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        while True:
            try:
                conn.connect(self._sock_path)
                break
            except OSError:
                if time.time() > deadline or self._proc.poll() is not None:
                    raise RuntimeError("zygote failed to start")
                time.sleep(0.05)
        conn.settimeout(120.0)  # first request waits on warm imports
        self._conn = conn
        self._alive = set()
        self._exited = {}
        self._polled_at = 0.0

    def _request(self, req: dict, *, start: bool = False) -> dict:
        with self._lock:
            self._ensure(start)
            try:
                if self._stale_nonces and req.get("op") != "reap_stale":
                    # Same-connection ordering guarantees the reap runs
                    # after any still-buffered stale spawn it names.
                    _send_msg(self._conn, {"op": "reap_stale",
                                           "nonces": list(self._stale_nonces)})
                    r = _recv_msg(self._conn)
                    if r is not None and "error" not in r:
                        self._stale_nonces.clear()
                _send_msg(self._conn, req)
                reply = _recv_msg(self._conn)
            except OSError as e:
                self._broken = True
                raise RuntimeError(f"zygote connection lost: {e}")
            if reply is None:
                self._broken = True
                raise RuntimeError("zygote closed the connection")
            if "error" in reply:
                raise RuntimeError(f"zygote: {reply['error']}")
            self._broken = False
            if self._conn.gettimeout() != 5.0:
                # Only the FIRST request may wait on warm imports; after
                # that, callers (some under the head's global lock, e.g.
                # worker sweeps doing proc.poll()) must never block long
                # on a wedged template.
                self._conn.settimeout(5.0)
            return reply

    # -- operations --------------------------------------------------------

    def spawn(self, *, env: dict, log_base: str, cwd: str) -> ZygoteProc:
        if not self._ready:
            self.prewarm()
            raise RuntimeError("zygote template not ready yet")
        nonce = os.urandom(8).hex()
        try:
            reply = self._request(
                {"op": "spawn", "env": env, "log_base": log_base,
                 "cwd": cwd, "nonce": nonce})
        except RuntimeError:
            # Template died/hiccuped: stop routing spawns here (callers
            # fall back to Popen) and re-warm in the background.  The
            # request may still execute out of the socket buffer later —
            # remember the nonce so the fork gets reaped, not adopted.
            with self._lock:
                self._stale_nonces[nonce] = None
                while len(self._stale_nonces) > 1024:
                    self._stale_nonces.pop(next(iter(self._stale_nonces)))
            self._ready = False
            self.prewarm()
            raise
        pid = reply["pid"]
        with self._lock:
            self._alive.add(pid)
            self._exited.pop(pid, None)  # pid reuse: drop stale exit record
        return ZygoteProc(self, pid)

    def exit_code(self, pid: int) -> int:
        """Recorded exit code for a pid known to be gone (-1 if the
        template never reported one, e.g. it died before reaping)."""
        with self._lock:
            return self._exited.get(pid, -1)

    def status(self, pid: int) -> Optional[int]:
        """Exit code if the worker has exited, else None (= running).
        A transient template hiccup must NOT read as worker death — the
        caller (ZygoteProc.poll) has already os.kill(pid, 0)-checked
        that the process exists, so on template trouble we report
        'running' and let the next poll retry."""
        now = time.time()
        with self._lock:
            if pid in self._exited:
                return self._exited[pid]
            if now - self._polled_at < self._POLL_CACHE_S \
                    and pid in self._alive:
                return None
        try:
            reply = self._request({"op": "poll_all"})
        except RuntimeError:
            return None  # process exists (caller checked); template flaky
        with self._lock:
            self._alive = set(reply["alive"])
            for p, code in reply["exited"].items():
                self._exited[int(p)] = code
            if len(self._exited) > 8192:
                for old in list(self._exited)[:4096]:
                    del self._exited[old]
            self._polled_at = now
            if pid in self._exited:
                return self._exited[pid]
            # Not this template's child (restarted template) but the
            # process exists per the caller's os.kill check: running.
            return None

    def kill(self, pid: int, sig: int) -> None:
        # Direct signal: pids are host pids and several callers hold
        # control-plane locks expecting Popen's non-blocking kill() —
        # the template only REAPS (its waitpid loop collects the exit).
        try:
            os.kill(pid, sig)
        except ProcessLookupError:
            pass

    def shutdown(self) -> None:
        with self._lock:
            if self._conn is None:
                return
            try:
                _send_msg(self._conn, {"op": "shutdown"})
                self._conn.settimeout(5.0)
                _recv_msg(self._conn)
            except OSError:
                pass
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None
            if self._proc is not None:
                try:
                    self._proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
                self._proc = None


_HANDLE: Optional[ZygoteHandle] = None
_HANDLE_LOCK = threading.Lock()


def get_zygote() -> ZygoteHandle:
    global _HANDLE
    with _HANDLE_LOCK:
        if _HANDLE is None:
            _HANDLE = ZygoteHandle()
            import atexit

            atexit.register(_HANDLE.shutdown)
        return _HANDLE


if __name__ == "__main__":
    main()
