"""Runtime context: who/where am I, from inside a task or actor.

Counterpart of python/ray/runtime_context.py (ray.get_runtime_context():
job/node/worker/actor ids, resource view). Answers come from the local
runtime object — the worker already knows its identity; nothing round-
trips to the control plane except the node listing.
"""

from __future__ import annotations

from typing import Dict, Optional


class RuntimeContext:
    def __init__(self, runtime):
        self._rt = runtime

    @property
    def worker_id(self) -> str:
        return self._rt.core.worker_hex

    @property
    def session_id(self) -> str:
        return self._rt.core.session_id

    @property
    def node_id(self) -> str:
        import os

        return os.environ.get("RAY_TPU_NODE_ID", "head")

    @property
    def job_id(self) -> str:
        import os

        return os.environ.get("RAY_TPU_JOB_ID", "")

    @property
    def namespace(self) -> str:
        return getattr(self._rt, "namespace", "")

    def get_actor_id(self) -> Optional[str]:
        """Hex id of the current actor, or None outside an actor."""
        hex_id = getattr(self._rt, "_actor_hex", "")
        return hex_id or None

    def get_task_id(self) -> Optional[str]:
        """Hex id of the currently executing task (worker-side), or None
        on the driver."""
        spec = getattr(self._rt, "_current_task_spec", None)
        if spec is not None and spec.task_id is not None:
            return spec.task_id.hex()
        return None

    @property
    def was_current_actor_reconstructed(self) -> bool:
        import os

        return os.environ.get("RAY_TPU_ACTOR_RESTARTED", "0") == "1"

    def get_assigned_resources(self) -> Dict[str, float]:
        spec = getattr(self._rt, "_current_task_spec", None)
        if spec is not None:
            return dict(spec.resources)
        return {}

    def get_node_ids(self):
        return [n["node_id"] for n in self._rt.state_list("nodes")]


def get_runtime_context() -> RuntimeContext:
    from ray_tpu.core.runtime import get_runtime

    return RuntimeContext(get_runtime())
