"""ObjectRef: a first-class future handle to an object in the cluster.

Counterpart of the reference's ObjectRef (python/ray/_raylet.pyx ObjectRef +
src/ray/core_worker/reference_count.h).  Holds the object id plus owner hint;
pickling an ObjectRef routes through module-level hooks so the serializer can
record borrowed refs and the deserializer can re-register them with the
runtime (the borrowing protocol's Python edge).
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu.core.ids import ObjectID

_local = threading.local()


def _push_capture_list(lst):
    prev = getattr(_local, "capture", None)
    _local.capture = lst
    return prev


def _pop_capture_list(prev):
    _local.capture = prev


def _push_ref_resolver(fn):
    prev = getattr(_local, "resolver", None)
    _local.resolver = fn
    return prev


def _pop_ref_resolver(prev):
    _local.resolver = prev


def _reconstruct_ref(hex_id: str, owner: Any):
    resolver = getattr(_local, "resolver", None)
    ref = ObjectRef(ObjectID.from_hex(hex_id), owner=owner)
    if resolver is not None:
        resolver(ref)
    return ref


class ObjectRef:
    __slots__ = ("_id", "_owner", "_hex", "__weakref__")

    def __init__(self, object_id: ObjectID, owner=None):
        self._id = object_id
        self._owner = owner
        # Precomputed: hot paths (wait partition scans) read the
        # attribute directly instead of two method calls per ref.
        self._hex = object_id.hex()

    @property
    def id(self) -> ObjectID:
        return self._id

    @property
    def owner(self):
        return self._owner

    def hex(self) -> str:
        return self._hex

    def binary(self) -> bytes:
        return self._id.binary()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        capture = getattr(_local, "capture", None)
        if capture is not None:
            capture.append(self._id.hex())
        return (_reconstruct_ref, (self._id.hex(), self._owner))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().as_future(self)

    def __await__(self):
        import asyncio

        fut = self.future()
        return asyncio.wrap_future(fut).__await__()

    def __del__(self):
        try:
            from ray_tpu.core.runtime import _global_runtime

            if _global_runtime is not None:
                _global_runtime.on_ref_deleted(self._id)
        except Exception:
            pass
