"""Central registry of every ``RAY_TPU_*`` environment knob.

Counterpart of the reference's generated flag table
(``ray_config_def.h``): one declaration per knob — name, typed default,
scope, one-line doc.  Two kinds of knob exist:

  * **explicit knobs** — read directly via ``os.environ`` somewhere in
    the tree; declared below as literal ``Knob(...)`` entries (literal
    so raylint's knob pass can extract them without importing).
  * **Config-derived knobs** — every field of ``core/config.py``'s
    ``Config`` dataclass is an implicit ``RAY_TPU_<FIELD>`` override
    via ``_env_override``; their docs live in ``_CONFIG_DOCS`` and the
    defaults/types come from the dataclass itself.

Conformance is enforced by ``python -m ray_tpu.analysis`` (the
``knobs`` pass), bidirectionally: a ``RAY_TPU_*`` name used anywhere in
ray_tpu/, scripts/ or tests/ must be declared here AND documented in
README's "Configuration knobs" table; a knob declared here must be
read somewhere (dead knobs fail).  README's table is generated —
regenerate with ``python -m ray_tpu.analysis --print-knob-table``.

Scopes: ``user`` (operator-facing tuning/feature gates), ``internal``
(set by the system for child processes; not meant for operators),
``bench`` (benchmark scripts only), ``test`` (test harness only).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str          # full env-var name (RAY_TPU_...)
    default: str       # default as the env string ("" = unset)
    type: str          # "str" | "int" | "float" | "bool" | "flag"
    scope: str         # "user" | "internal" | "bench" | "test"
    doc: str           # one line


KNOBS: List[Knob] = [
    # -- cluster / process identity (mostly set by the spawner) ----------
    Knob("RAY_TPU_ADDRESS", "", "str", "user",
         "Cluster address for init() when no address argument is given."),
    Knob("RAY_TPU_NAMESPACE", "", "str", "internal",
         "Namespace a spawned worker joins (set by the node manager)."),
    Knob("RAY_TPU_NODE_ID", "head", "str", "internal",
         "Node id of this process (exported to workers and node managers)."),
    Knob("RAY_TPU_JOB_ID", "", "str", "internal",
         "Job id exported to workers for runtime_context.get_job_id()."),
    Knob("RAY_TPU_WORKER_ID", "", "str", "internal",
         "Worker id (hex) assigned to a spawned worker process."),
    Knob("RAY_TPU_WORKER_KIND", "pool", "str", "internal",
         "Spawned worker flavor: pool (stateless tasks) or actor."),
    Knob("RAY_TPU_CONTROL_ADDR", "", "str", "internal",
         "Head control-server address handed to spawned workers."),
    Knob("RAY_TPU_LOCAL_NM", "", "str", "internal",
         "Local node-manager address a worker dials for the object plane."),
    Knob("RAY_TPU_ENV_KEY", "", "str", "internal",
         "Runtime-env key assigned to a spawned worker."),
    Knob("RAY_TPU_ACTOR_RESTARTED", "0", "bool", "internal",
         "Set on restarted actor workers; read by "
         "was_current_actor_restarted()."),
    Knob("RAY_TPU_CONTAINER_IMAGE", "", "str", "internal",
         "Exported into container runtime-envs so user code can learn "
         "its image."),

    # -- accelerators ----------------------------------------------------
    Knob("RAY_TPU_CHIPS", "", "str", "user",
         "Comma-separated TPU chip ids visible to this process "
         "(fallback for TPU_VISIBLE_CHIPS)."),
    Knob("RAY_TPU_ACCELERATOR_TYPE", "", "str", "user",
         "Pod type override (v4-16, ...) when TPU metadata is "
         "unavailable."),
    Knob("RAY_TPU_NO_METADATA", "0", "bool", "user",
         "1 skips GCE metadata-server queries during TPU detection."),
    Knob("RAY_TPU_PALLAS_INTERPRET", "", "flag", "user",
         "Run Pallas kernels in interpret mode (CPU-only testing)."),
    Knob("RAY_TPU_PREFILL_DENSE", "", "flag", "user",
         "1 forces the dense prefill path in models/decoding."),
    Knob("RAY_TPU_PA_SB", "", "int", "bench",
         "Paged-attention sub-batch override (perf experiments only)."),
    Knob("RAY_TPU_NATIVE_SANITIZE", "", "str", "user",
         "Build the native extension with this sanitizer (asan/tsan)."),
    Knob("RAY_TPU_NATIVE_STORE", "1", "bool", "user",
         "0 disables the C++ shared-memory object-store fast path."),

    # -- rpc / wire ------------------------------------------------------
    Knob("RAY_TPU_RPC_NO_BATCH", "", "flag", "user",
         "1 disables control-plane frame coalescing (legacy protocol)."),
    Knob("RAY_TPU_RPC_BATCH_MAX_MSGS", "512", "int", "user",
         "Max sub-messages per coalesced control-plane batch frame."),
    Knob("RAY_TPU_RPC_BATCH_MAX_BYTES", "4194304", "int", "user",
         "Flush threshold (bytes) for the control-plane coalescing "
         "buffer."),
    Knob("RAY_TPU_RPC_FLUSH_US", "0", "int", "user",
         "Microseconds the coalescing sender lingers before flushing so "
         "ping-pong request/ack chains batch; 0 keeps first-message-"
         "immediate."),

    # -- serve -----------------------------------------------------------
    Knob("RAY_TPU_SERVE_MAX_QUEUE", "1024", "int", "user",
         "Engine admission cap: add_request raises QueueFull once this "
         "many requests wait (0 = unbounded)."),
    Knob("RAY_TPU_SERVE_QUEUE_TIMEOUT_S", "60", "float", "user",
         "Default queueing deadline; requests still waiting past it are "
         "shed at the next engine step (0 = never)."),
    Knob("RAY_TPU_SERVE_PREFILL_BUDGET", "8192", "int", "user",
         "Per-step prefill token budget the continuous-batching "
         "scheduler may spend while decode slots are live (0 = "
         "unlimited)."),
    Knob("RAY_TPU_SERVE_FEEDBACK_STALE_S", "5", "float", "user",
         "Age past which a replica's piggybacked load report is ignored "
         "and routing falls back to local inflight counts."),
    Knob("RAY_TPU_SERVE_LOAD_REPORT_S", "1", "float", "user",
         "Interval between controller load-report probes of serve "
         "replicas."),
    Knob("RAY_TPU_GRPC_WORKERS", "16", "int", "user",
         "Thread-pool size of the serve gRPC proxy's request executor."),
    Knob("RAY_TPU_SERVE_ROLE_STRICT", "0", "bool", "user",
         "1 makes phase-tagged requests WAIT for a replica of their "
         "role instead of degrading to mixed routing on an empty pool."),
    Knob("RAY_TPU_SERVE_HANDOFF_TIMEOUT_S", "30", "float", "user",
         "Timeout for pulling a prefill->decode KV bundle off the "
         "object plane (and the disagg client's per-leg timeout) "
         "before falling back to re-prefill."),
    Knob("RAY_TPU_SERVE_DIGEST_K", "16", "int", "user",
         "Top-K hot prefix keys a serve replica advertises in its "
         "load-report digest for prefix-locality routing."),
    Knob("RAY_TPU_SERVE_TRACE", "1", "bool", "user",
         "0 disables request-journey tracing at the serve ingress "
         "proxies (no trace minting, no per-request phase spans)."),
    Knob("RAY_TPU_SERVE_SLO_SAMPLES", "256", "int", "user",
         "Capacity of the per-engine SLO sample ring (TTFT/TPOT/queue-"
         "wait) drained by load reports between controller probes."),
    Knob("RAY_TPU_SERVE_STEP_SAMPLE_EVERY", "8", "int", "user",
         "Engine step-sampler cadence: every Nth step snapshots batch "
         "occupancy, queue depth, free KV pages and prefill token "
         "spend (0 disables)."),
    Knob("RAY_TPU_SERVE_SLO_WINDOW_S", "300", "float", "user",
         "Sliding-window width of the controller's per-deployment SLO "
         "percentiles (serve_slo / /api/serve_slo)."),

    # -- scheduling / placement -----------------------------------------
    Knob("RAY_TPU_NO_LOCALITY", "", "flag", "user",
         "Truthy disables locality-aware task placement on the head."),
    Knob("RAY_TPU_GCS_SHARDS", "8", "int", "user",
         "Owner-keyed submit-ingress shards on the head (0 = legacy "
         "single-lock ingress)."),
    Knob("RAY_TPU_NODE_INDEX", "1", "bool", "user",
         "0 disables the utilization-bucketed node index and falls back "
         "to full node-table scans in _pick_node/placement."),
    Knob("RAY_TPU_SCHED_IDLE_WAIT_S", "30.0", "float", "user",
         "Scheduler wakeup ceiling when no time-based work is pending "
         "(timer-wheel deadlines cover lease expiry below this)."),
    Knob("RAY_TPU_ZEROCOPY_MIN_BYTES", "524288", "int", "user",
         "Payloads at/above this ride the scatter-gather wire path "
         "(no header+payload concat copy); 0 disables."),
    Knob("RAY_TPU_NM_PULL", "1", "bool", "user",
         "0 disables node-manager-level single-flight object pulls; "
         "workers pull remote objects directly."),
    Knob("RAY_TPU_GIL_SWITCH_S", "0", "float", "user",
         "sys.setswitchinterval applied at process start (0 = keep the "
         "interpreter default, 5ms); opt-in tuning for hosts running "
         "many ray_tpu processes per core."),
    Knob("RAY_TPU_DISABLE_ZYGOTE", "0", "bool", "user",
         "1 disables the zygote prefork path; workers spawn directly."),
    Knob("RAY_TPU_WHEEL_DIR", "", "str", "user",
         "Directory of pre-built wheels for runtime-env pip installs."),

    # -- observability ---------------------------------------------------
    Knob("RAY_TPU_LOGGING_CONFIG", "", "str", "user",
         "JSON logging config applied at process start "
         "(core/logging_config.py)."),
    Knob("RAY_TPU_METRICS_TTL_S", "60", "float", "user",
         "Staleness window for per-worker metric snapshots in /metrics "
         "aggregation."),
    Knob("RAY_TPU_TRACE_MAX_SPANS", "100000", "int", "user",
         "Per-process cap on buffered trace spans."),
    Knob("RAY_TPU_FLIGHT_RECORDER", "1", "bool", "user",
         "0 disables the in-process flight-recorder event ring."),
    Knob("RAY_TPU_FLIGHT_RECORDER_MAX_EVENTS", "4096", "int", "user",
         "Flight-recorder ring capacity (events)."),
    Knob("RAY_TPU_USAGE_STATS_ENABLED", "1", "bool", "user",
         "0 disables anonymous usage-stats collection."),
    Knob("RAY_TPU_PROFILE_SAMPLER", "1", "bool", "user",
         "0 disables the worker's background profile sampler."),
    Knob("RAY_TPU_PROFILE_SAMPLE_INTERVAL_S", "5", "float", "user",
         "Interval between worker profile-sampler snapshots."),
    Knob("RAY_TPU_SPAN_HARVEST_CHUNK", "2048", "int", "user",
         "Spans per chunk when the head harvests worker span buffers."),
    Knob("RAY_TPU_SPAN_HARVEST_MAX_CHUNKS", "8", "int", "user",
         "Max chunks pulled from one worker per harvest round."),
    Knob("RAY_TPU_SPAN_STORE_MAX", "200000", "int", "user",
         "Head-side cap on retained harvested spans."),
    Knob("RAY_TPU_OPS_JOURNAL_DIR", "", "str", "user",
         "Directory for the durable ops journal (spans/flight/metrics "
         "streams); unset disables journaling."),
    Knob("RAY_TPU_OPS_JOURNAL_MAX_BYTES", "67108864", "int", "user",
         "Per-stream on-disk retention budget; oldest journal segments "
         "are deleted past it."),
    Knob("RAY_TPU_OPS_JOURNAL_ROTATE_S", "600", "float", "user",
         "Max age of one journal segment before it rotates."),
    Knob("RAY_TPU_OPS_JOURNAL_FSYNC_S", "0.2", "float", "user",
         "Journal writer batch interval: queued records are written "
         "and fsynced at most this often."),
    Knob("RAY_TPU_PROFILE_HISTORY", "120", "int", "user",
         "Per-worker profile samples retained in the head's history "
         "ring for /api/profile percentiles."),

    # -- straggler / health watchdog (core/gcs.py) -----------------------
    Knob("RAY_TPU_WATCHDOG", "1", "bool", "user",
         "0 disables the head's straggler/health watchdog."),
    Knob("RAY_TPU_WATCHDOG_INTERVAL_S", "5.0", "float", "user",
         "Watchdog tick period (floor 0.05)."),
    Knob("RAY_TPU_WATCHDOG_MIN_SAMPLES", "5", "int", "user",
         "Completed-task samples required before straggler scoring."),
    Knob("RAY_TPU_WATCHDOG_PERCENTILE", "95.0", "float", "user",
         "Percentile of past durations used as the straggler baseline."),
    Knob("RAY_TPU_WATCHDOG_MULTIPLIER", "3.0", "float", "user",
         "A task is a straggler past baseline x this multiplier."),
    Knob("RAY_TPU_WATCHDOG_MIN_AGE_S", "1.0", "float", "user",
         "Tasks younger than this are never flagged as stragglers."),
    Knob("RAY_TPU_WATCHDOG_HEARTBEAT_TIMEOUT_S", "30.0", "float", "user",
         "Worker heartbeat silence before it is marked unhealthy."),

    # -- device-plane telemetry (util/device_stats.py) -------------------
    Knob("RAY_TPU_DEVICE_STATS", "1", "bool", "user",
         "0 disables device-plane telemetry (compile-event hook, "
         "roofline/MFU step accounting)."),
    Knob("RAY_TPU_DEVICE_RECOMPILE_WARMUP", "2", "int", "user",
         "Compilations of one jitted function tolerated as warmup "
         "before counting toward recompile churn."),
    Knob("RAY_TPU_DEVICE_RECOMPILE_MAX", "8", "int", "user",
         "Post-warmup compiles of one function on one worker past "
         "which the watchdog flags a recompile storm."),
    Knob("RAY_TPU_DEVICE_HBM_WATERMARK", "0.9", "float", "user",
         "Device-memory occupancy watermark fraction at/over which "
         "the watchdog raises an HBM health alert."),
    Knob("RAY_TPU_DEVICE_HBM_GBPS", "0", "float", "user",
         "HBM bandwidth override (GB/s) for the roofline model; 0 "
         "selects the built-in per-device-kind table."),
    Knob("RAY_TPU_DEVICE_PEAK_TFLOPS", "0", "float", "user",
         "Peak dense TFLOP/s override for MFU; 0 selects the built-in "
         "per-device-kind table."),
    Knob("RAY_TPU_DEVICE_HBM_BYTES", "0", "int", "user",
         "Device-memory capacity override (bytes) for the HBM ledger "
         "on backends without memory_stats (e.g. CPU)."),

    # -- libraries -------------------------------------------------------
    Knob("RAY_TPU_DATA_BLOCK_FORMAT", "arrow", "str", "user",
         "Default block format for ray_tpu.data datasets."),
    Knob("RAY_TPU_WORKFLOW_STORAGE", "", "str", "user",
         "Workflow checkpoint root (default: <tmpdir>/ray_tpu/"
         "workflows)."),
    Knob("RAY_TPU_COPY_DESER_BUFFERS", "0", "bool", "user",
         "1 copies deserialized buffers out of shm instead of zero-copy "
         "views."),

    # -- benchmarks (scripts/) -------------------------------------------
    Knob("RAY_TPU_BENCH_SCALE", "1.0", "float", "bench",
         "Scales microbenchmark workload sizes."),
    Knob("RAY_TPU_BENCH_HARVEST", "1", "bool", "bench",
         "0 disables span harvest during bench_profiling runs."),
    Knob("RAY_TPU_BENCH_SAMPLER", "1", "bool", "bench",
         "0 disables the profile sampler during bench_profiling runs."),
    Knob("RAY_TPU_BENCH_LATENCY_MS", "15", "float", "bench",
         "Simulated cross-node link latency in bench_object_plane."),
    Knob("RAY_TPU_BENCH_PG_NODES", "2000", "int", "bench",
         "Simulated-cluster node count for bench_head_scale's "
         "placement-group section."),

    # -- test harness (tests/conftest.py) --------------------------------
    Knob("RAY_TPU_TEST_WATCHDOG", "420", "int", "test",
         "Per-test hang watchdog (seconds); 0 disables."),
    Knob("RAY_TPU_TEST_WATCHDOG_LOG", "/tmp/ray_tpu_test_watchdog.log",
         "str", "test",
         "Where the test watchdog dumps stacks on a hang."),
]

# One-line docs for the Config-derived knobs (RAY_TPU_<FIELD> via
# config._env_override).  Keys MUST mirror the Config dataclass fields
# — raylint's knobs pass fails on drift in either direction.
_CONFIG_DOCS: Dict[str, str] = {
    "max_inline_object_size":
        "Objects at/below this size are inlined in the object directory.",
    "max_direct_result_bytes":
        "Actor results at/below this ride the direct connection back.",
    "object_store_memory":
        "Shared-memory store capacity in bytes (0 = bounded by /dev/shm).",
    "shm_dir": "Directory backing the shared-memory store.",
    "object_spilling_threshold":
        "Spill shm objects past this usage fraction (0 disables).",
    "spill_storage":
        "Spill target: '' = <session>/spilled, a path, or an URI prefix.",
    "spill_min_age_s": "Objects younger than this are not spilled.",
    "enable_object_reconstruction":
        "Re-execute the producing task when an object's only copy is "
        "lost.",
    "object_reconstruction_max_attempts":
        "Per-object cap on reconstruction re-executions.",
    "max_lineage_entries":
        "Cap on retained task records + lineage links before eviction.",
    "memory_usage_threshold":
        "OOM-kill retriable tasks past this host-memory fraction "
        "(0 disables).",
    "memory_monitor_refresh_s": "Memory-monitor poll period.",
    "oom_kill_cooldown_s": "Minimum seconds between OOM kills.",
    "memory_usage_threshold_critical":
        "Past this fraction, non-retriable tasks become kill-eligible "
        "too.",
    "prestart_workers": "Worker processes started eagerly at init.",
    "max_workers_per_node": "Hard cap on worker processes per node.",
    "worker_lease_timeout_s":
        "Seconds a leased idle worker is kept before returning to the "
        "pool.",
    "scheduler_top_k_fraction":
        "Top-k random choice fraction among feasible nodes.",
    "direct_task_leases":
        "Owner-direct task leases; off = every task transits the head.",
    "lease_pipeline_depth": "In-flight pipeline depth per leased worker.",
    "lease_idle_timeout_s":
        "Owner returns an idle lease after this long without queued "
        "work.",
    "max_lease_workers_per_request":
        "Cap on workers one lease request asks for.",
    "lease_scaleup_clamp_s":
        "How long an unanswered lease ask clamps pipeline depth to 1.",
    "task_max_retries": "Default retry budget for failed tasks.",
    "actor_max_restarts": "Default restart budget for crashed actors.",
    "health_check_period_s": "Node health-check probe period.",
    "health_check_timeout_s": "Node health-check failure timeout.",
    "rpc_connect_timeout_s": "Control-plane dial timeout.",
    "rpc_max_message_bytes": "Hard cap on one control-plane frame.",
    "node_ip_address": "Address this host's rpc servers bind.",
    "node_advertise_ip":
        "Address advertised to peers ('' = node_ip_address).",
    "transfer_chunk_bytes": "Chunk size for cross-node object pulls.",
    "pull_window": "In-flight fetch_chunk requests per object pull.",
    "worker_register_timeout_s":
        "A spawned worker silent past this is presumed dead and its "
        "work retried.",
    "gcs_store_path":
        "Path for the control server's KV journal ('' = in-memory "
        "only).",
    "control_port": "Fixed control-server port (0 = ephemeral).",
    "gcs_reconnect_timeout_s":
        "How long clients retry redialing a lost head (0 disables).",
    "head_restart_grace_s":
        "Grace for restored-but-unclaimed entities after a head "
        "restart.",
    "log_dir": "Per-session log directory ('' = session default).",
}


def apply_interpreter_tuning() -> None:
    """Per-process interpreter tuning, called from every bootstrap path
    (driver init, worker main, node-manager main).

    RAY_TPU_GIL_SWITCH_S shortens the GIL switch interval: an op on the
    hot path crosses several processes (owner -> head -> worker ->
    owner), and on an oversubscribed host each hop's recv-thread wakeup
    can wait out the full default 5 ms interval before the bytecode
    holder yields — a latency tax that bounds end-to-end throughput
    even when every process profiles as idle."""
    import os
    import sys

    try:
        si = float(os.environ.get("RAY_TPU_GIL_SWITCH_S", "0") or 0)
    except ValueError:
        si = 0.0
    if si > 0:
        sys.setswitchinterval(si)


def config_knobs() -> List[Knob]:
    """The Config-derived knobs, materialized with the dataclass
    defaults (import-time cheap: config has no heavy deps)."""
    from ray_tpu.core import config as _config

    out = []
    for f in dataclasses.fields(_config.Config):
        doc = _CONFIG_DOCS.get(f.name, "")
        default = f.default
        tname = type(default).__name__
        out.append(Knob(
            name=f"RAY_TPU_{f.name.upper()}",
            default=str(default),
            type=tname if tname in ("int", "float", "bool", "str")
            else "str",
            scope="user",
            doc=doc))
    return out


def all_knobs() -> List[Knob]:
    seen = set()
    out = []
    for k in list(KNOBS) + config_knobs():
        if k.name not in seen:
            seen.add(k.name)
            out.append(k)
    return sorted(out, key=lambda k: (k.scope, k.name))


def get(name: str) -> Optional[Knob]:
    for k in all_knobs():
        if k.name == name:
            return k
    return None


def render_readme_table() -> str:
    """The README 'Configuration knobs' section body, generated so docs
    cannot drift from the registry (raylint checks both directions)."""
    lines = [
        "",
        "All runtime tuning rides `RAY_TPU_*` environment variables, "
        "declared centrally in",
        "`ray_tpu/core/knobs.py` (`Config` fields in "
        "`ray_tpu/core/config.py` are implicit",
        "`RAY_TPU_<FIELD>` overrides).  Generated by "
        "`python -m ray_tpu.analysis --print-knob-table`;",
        "the `knobs` lint pass fails on any drift between code, "
        "registry, and this table.",
        "",
    ]
    titles = {"user": "Operator knobs",
              "internal": "Internal (set by the system)",
              "bench": "Benchmark scripts",
              "test": "Test harness"}
    by_scope: Dict[str, List[Knob]] = {}
    for k in all_knobs():
        by_scope.setdefault(k.scope, []).append(k)
    for scope in ("user", "internal", "bench", "test"):
        knobs = by_scope.get(scope)
        if not knobs:
            continue
        lines.append(f"### {titles[scope]}")
        lines.append("")
        lines.append("| Variable | Default | Type | Meaning |")
        lines.append("|---|---|---|---|")
        for k in knobs:
            default = k.default if k.default != "" else "*(unset)*"
            lines.append(
                f"| `{k.name}` | `{default}` | {k.type} | {k.doc} |")
        lines.append("")
    return "\n".join(lines) + "\n"
