"""Per-node manager daemon: the raylet counterpart for worker hosts.

A NodeManager joins an existing cluster head (`ray-tpu start
--address=<head>`), registers this host's resources, and then:

  - spawns/supervises the local worker pool when the head's scheduler
    places work on this node (reference WorkerPool::StartWorkerProcess,
    src/ray/raylet/worker_pool.h:159),
  - owns the node-local shared-memory arena (the embedded plasma store of
    a raylet, src/ray/object_manager/plasma/store_runner.h) that this
    node's workers read/write,
  - serves chunked object fetches to other nodes/the head over the frame
    protocol (reference ObjectManager::Push/HandlePull,
    src/ray/object_manager/object_manager.h:206/:139),
  - sweeps dead-process pins from its arena (plasma client-disconnect
    accounting).

The head keeps the cluster-wide object *directory* (who has what) and
does location lookup; the bulk bytes move node-to-node without transiting
the head (reference OwnershipBasedObjectDirectory + direct raylet-to-
raylet transfer).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, Optional

from ray_tpu.core import object_plane, rpc
from ray_tpu.core.config import get_config, reset_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.resources import node_resources_from_env


def zygote_enabled() -> bool:
    return os.environ.get("RAY_TPU_DISABLE_ZYGOTE", "") != "1"


def cpu_worker_env(env: dict) -> dict:
    """CPU-class worker environment, shared by exec spawns and the zygote
    template so fork spawns stay environment-identical to exec spawns:
    skip sitecustomize's jax/TPU grab (the `-S` interpreter needs
    site-packages restored via PYTHONPATH), line-visible output, and the
    pyarrow jemalloc guard (bundled jemalloc segfaults on this kernel)."""
    from ray_tpu.core.gcs import _site_packages

    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")
    extra = [p for p in (_site_packages(), env.get("PYTHONPATH")) if p]
    if extra:
        env["PYTHONPATH"] = os.pathsep.join(extra)
    return env


def prewarm_zygote() -> None:
    """Start warming this process's worker template (no-op when disabled)."""
    if not zygote_enabled():
        return
    try:
        from ray_tpu.core.zygote import get_zygote

        get_zygote().prewarm()
    except Exception:
        pass


def _has_exec_only_env_vars(runtime_env: Optional[dict]) -> bool:
    """True when a runtime_env's env_vars only take effect at exec time —
    dynamic-loader paths, interpreter flags, native thread-pool init —
    and so would be silently inert in a forked zygote child whose
    interpreter and native libs are already loaded.  Such spawns keep the
    Popen path (mirroring the JAX_PLATFORMS special case above) so the
    same runtime_env behaves identically warm or cold."""
    if not runtime_env:
        return False
    env_vars = runtime_env.get("env_vars") or {}
    for k in env_vars:
        if k.startswith(("LD_", "PYTHON", "OMP_", "OPENBLAS_", "MKL_",
                         "MALLOC_", "GOMP_", "XLA_FLAGS")):
            return True
    return False


def spawn_worker_process(*, control_addr: str, worker_hex: str, kind: str,
                         env_key: str, namespace: str, node_id: str,
                         log_dir: str, session_id: str,
                         extra_env: Optional[dict] = None,
                         runtime_env: Optional[dict] = None
                         ) -> subprocess.Popen:
    """Start one worker process (shared by the head's in-process pool and
    remote node managers — reference worker_pool.h StartWorkerProcess).

    A runtime_env carrying a `container` spec wraps the command so the
    worker boots chrooted into the image rootfs inside a private
    user+mount namespace (runtime_env/container.py — the reference
    applies its podman prefix at the same point, worker_pool / image_uri)."""
    env = dict(os.environ)
    env["RAY_TPU_CONTROL_ADDR"] = control_addr
    env["RAY_TPU_WORKER_ID"] = worker_hex
    env["RAY_TPU_WORKER_KIND"] = kind
    env["RAY_TPU_ENV_KEY"] = env_key
    env["RAY_TPU_NAMESPACE"] = namespace
    env["RAY_TPU_NODE_ID"] = node_id
    # Line-visible worker output (see gcs.py _spawn_worker).
    env["PYTHONUNBUFFERED"] = "1"
    # pyarrow's bundled jemalloc segfaults under this kernel.
    env.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, "-m", "ray_tpu.core.worker"]
    cpu_class = env_key.startswith("tpu0") or not env_key.startswith("tpu")
    if cpu_class:
        # CPU-only worker: skip site init (sitecustomize imports jax).
        cpu_worker_env(env)
        cmd = [sys.executable, "-S", "-m", "ray_tpu.core.worker"]
    os.makedirs(log_dir, exist_ok=True)
    log_base = os.path.join(log_dir, f"worker-{worker_hex[:8]}")
    # Fork-from-warm-template fast path (core/zygote.py): the common CPU
    # worker class skips interpreter startup + imports entirely.  Exec
    # paths remain for container envs (chroot wrapper), envs that swap
    # package resolution (pip/conda/py_modules pins would be shadowed by
    # the template's pre-imported modules in sys.modules), TPU workers
    # (sitecustomize), and as the fallback whenever the template is cold
    # (spawn() raises until the template answers a ping — a warming
    # zygote must never add latency to a worker the scheduler waits on)
    # or broken.
    _zygote_safe_env_keys = {"env_vars", "working_dir", "excludes"}
    if (cpu_class
            and not (runtime_env
                     and set(runtime_env) - _zygote_safe_env_keys)
            and not (extra_env and "JAX_PLATFORMS" in extra_env)
            and not _has_exec_only_env_vars(runtime_env)
            and zygote_enabled()):
        try:
            from ray_tpu.core.zygote import get_zygote

            return get_zygote().spawn(env=env, log_base=log_base,
                                      cwd=os.getcwd())
        except Exception:
            pass
    if runtime_env and runtime_env.get("container"):
        from ray_tpu.runtime_env.container import build_container_command

        cmd = build_container_command(
            runtime_env["container"], cmd, cwd=os.getcwd(),
            shm_dir=get_config().shm_dir)
    stdout = open(log_base + ".out", "ab")
    stderr = open(log_base + ".err", "ab")
    # raylint: allow-blocking(fork+exec IS the lease-grant op's work; latency accepted by design)
    return subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr,
                            cwd=os.getcwd())


class NodeManager:
    """One per worker host; dies with the cluster (or when the head asks)."""

    def __init__(self, head_address: str, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[dict] = None, node_id: str = "",
                 labels: Optional[Dict[str, str]] = None):
        reset_config()
        self.config = get_config()
        self.head_address = head_address
        # The arena name must be unique per NODE, not per session: two
        # node managers simulated on one machine (tests) must not share
        # /dev/shm segments, or "remote" fetches silently read locally.
        self.store_key = f"node-{uuid.uuid4().hex[:12]}"
        self._stopped = threading.Event()
        # Head pushes (spawn_worker) and peer fetches can arrive the
        # moment register_node returns — before __init__ finishes
        # assigning session_dir/store below.  Handlers gate on this.
        self._ready = threading.Event()
        self._lock = threading.Lock()
        self._procs: Dict[str, subprocess.Popen] = {}
        # In-progress push-broadcast receptions: obj_hex -> [segment,
        # size, received_bytes, last_activity] (reaped by age in the
        # sweep loop so aborted senders don't leak arena memory).
        self._incoming: Dict[str, list] = {}
        # Synced cluster resource view (head broadcast; gcs.py
        # _sync_resource_view).
        self._view: Dict[str, dict] = {}
        self._view_seq = -1
        self._view_epoch = ""
        self._view_at = 0.0
        prewarm_zygote()  # template warms while the node registers
        self.server = rpc.Server(self._handle,
                                 host=self.config.node_ip_address)
        # Advertised (not bind) address: a 0.0.0.0 bind must not hand
        # peers an unroutable wildcard.
        self.address = (f"{self.config.advertised_host()}:"
                        f"{self.server.port}")
        node_res = node_resources_from_env(num_cpus, num_tpus, resources)
        self._register_msg = {
            "op": "register_node",
            "node_id": node_id,
            "resources": node_res.to_dict(),
            "address": self.address,
            "labels": labels or {},
            "store_key": self.store_key,
            "shm_dir": self.config.shm_dir,
        }
        self.head = rpc.Client(head_address, on_push=self._on_push)
        reply = self.head.call(self._register_msg)
        self.node_id = reply["node_id"]
        self.session_id = reply["session_id"]
        self.namespace = reply.get("namespace", "")
        self.session_dir = os.path.join(
            "/tmp/ray_tpu", f"session-{self.session_id}",
            f"node-{self.node_id}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.store = ShmObjectStore(self.store_key, self.config.shm_dir)
        # Node-level pull single-flight (op "pull_object"): all workers
        # on this host route remote fetches here, so N co-located
        # consumers of one object cost ONE wire transfer into the
        # shared arena (reference PullManager request coalescing at the
        # raylet, not the worker).
        self._pull_mgr = object_plane.PullManager()
        self._peer_conns: Dict[str, rpc.Client] = {}
        self._peer_lock = threading.Lock()
        self._ready.set()
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         name="node-sweep", daemon=True)
        self._sweeper.start()

    # -- head → node pushes --------------------------------------------
    def _on_push(self, msg: dict):
        self._ready.wait(timeout=60.0)
        op = msg.get("op")
        if op == "spawn_worker":
            try:
                proc = spawn_worker_process(
                    control_addr=self.head_address,
                    worker_hex=msg["worker_hex"], kind=msg["kind"],
                    env_key=msg["env_key"],
                    namespace=msg.get("namespace", self.namespace),
                    node_id=self.node_id,
                    log_dir=os.path.join(self.session_dir, "logs"),
                    session_id=self.session_id,
                    runtime_env=msg.get("runtime_env"),
                    # Local workers answer resource queries from this
                    # manager's synced view instead of dialing the head.
                    extra_env={"RAY_TPU_LOCAL_NM": self.address})
                with self._lock:
                    self._procs[msg["worker_hex"]] = proc
            except Exception as e:  # noqa: BLE001
                try:
                    self.head.send({"op": "worker_spawn_failed",
                                    "worker_hex": msg["worker_hex"],
                                    "error": f"{type(e).__name__}: {e}"})
                except Exception:
                    pass
        elif op == "kill_worker":
            with self._lock:
                proc = self._procs.pop(msg["worker_hex"], None)
            if proc is not None:
                try:
                    proc.kill()
                except OSError:
                    pass
        elif op == "resource_view":
            # Synced cluster resource view (N8, reference ray_syncer
            # RESOURCE_VIEW): newest seq per head epoch wins — a
            # restarted head's counter restarts, so a new epoch always
            # supersedes the old view.
            with self._lock:
                epoch = msg.get("epoch", "")
                if epoch != self._view_epoch:
                    self._view_epoch = epoch
                    self._view_seq = -1
                if msg["seq"] > self._view_seq:
                    self._view_seq = msg["seq"]
                    self._view = msg["nodes"]
                    self._view_at = time.time()
        elif op == "delete_object":
            # Cluster-wide refcount hit 0 (head decref/free): release the
            # local arena copy.
            try:
                self.store.delete(ObjectID.from_hex(msg["obj"]))
            except Exception:
                pass
        elif op == "migrate_objects":
            # Drain protocol (gcs.py _check_drains): push the listed
            # local arena objects to the survivor node's arena, then
            # report per-object results so the head can move the
            # primary-copy records before terminating this node.
            threading.Thread(target=self._migrate_and_report,
                             args=(msg,), daemon=True,
                             name="drain-migrate").start()
        elif op == "exit":
            self._stopped.set()

    def _migrate_and_report(self, msg: dict):
        from ray_tpu.core.object_plane import PushManager

        class _PushHost:
            """Adapter giving PushManager the runtime surface it needs
            (local store + cached peer connections + config)."""

            def __init__(self, nm):
                self.store = nm.store
                self.config = nm.config
                self._conns: Dict[str, rpc.Client] = {}

            def _node_conn(self, addr: str) -> rpc.Client:
                c = self._conns.get(addr)
                if c is None or c._closed:
                    c = self._conns[addr] = rpc.Client(
                        addr, connect_timeout=5.0)
                return c

        dest = msg["dest"]
        pm = PushManager(_PushHost(self))
        results: Dict[str, str] = {}
        for item in msg.get("objects", []):
            obj_hex, size = item["obj"], item["size"]
            try:
                res = pm.broadcast(obj_hex, size, [dest], timeout=300.0)
                results[obj_hex] = res.get(dest, "error: missing")
            except Exception as e:  # noqa: BLE001 — report, don't die
                results[obj_hex] = f"error: {type(e).__name__}: {e}"
        try:
            self.head.send({"op": "objects_migrated",
                            "node_id": self.node_id,
                            "dest_node": msg.get("dest_node", ""),
                            "results": results})
        except Exception:
            pass

    # -- peer/head → node requests (object plane) ----------------------
    def _handle(self, conn: rpc.Connection, msg: dict):
        self._ready.wait(timeout=60.0)
        op = msg.get("op")
        if op == "fetch_chunk":
            # Chunked pull of a locally stored object.  The segment stays
            # attached (cached in the store) until the object is deleted,
            # so concurrent chunk reads never race a release.
            oid = ObjectID.from_hex(msg["obj"])
            seg = self.store.attach(oid, msg["size"])
            off, n = msg["offset"], msg["length"]
            part = bytes(seg.buf[off:off + n])
            object_plane.OBJ._inc("bytes_pushed", len(part))
            return part
        if op == "has_object":
            return self.store.contains(ObjectID.from_hex(msg["obj"]))
        if op == "push_begin":
            # Push-broadcast receiver (core/object_plane.py PushManager;
            # reference ObjectManager::Push + HandlePush).  The whole
            # object is claimed up front: arena if it fits, the store's
            # file-backed overflow path otherwise (consumers still read
            # one mmap); a size the store cannot place at all REJECTS
            # so the sender fails fast instead of wedging mid-stream.
            oid = ObjectID.from_hex(msg["obj"])
            with self._lock:
                # The in-progress check comes BEFORE store.contains: a
                # file-spilled partial allocation already "exists" on
                # disk, and answering "have" for it would strand a
                # restarted sender with a truncated object forever.
                ent = self._incoming.get(msg["obj"])
                if ent is not None:
                    # Restarted sender (or a concurrent duplicate):
                    # chunk writes are idempotent rewrites of the same
                    # immutable bytes, and progress is a HIGH-WATER
                    # MARK (not a byte count), so re-streaming from
                    # offset 0 converges instead of double-counting.
                    ent[3] = time.monotonic()
                    return {"ok": True}
                if self.store.contains(oid):
                    return {"have": True}
                # Claim the slot BEFORE the (lock-free) create so a
                # concurrent duplicate can't double-create and orphan
                # the first segment; [segment, size, high-water mark,
                # last_activity, writes-in-progress].
                ent = self._incoming[msg["obj"]] = [
                    None, msg["size"], 0, time.monotonic(), 0]
            try:
                seg = self.store.create(oid, msg["size"])
            except Exception as e:  # noqa: BLE001 — nowhere to put it
                with self._lock:
                    self._incoming.pop(msg["obj"], None)
                return {"reject": f"{type(e).__name__}: {e}"}
            with self._lock:
                ent[0] = seg
            return {"ok": True}
        if op == "push_chunk":
            with self._lock:
                ent = self._incoming.get(msg["obj"])
                if ent is not None:
                    if ent[0] is None:
                        # Concurrent duplicate raced the creator's
                        # allocation window; this stream fails, the
                        # sender's retry converges.
                        raise ValueError(
                            f"push of {msg['obj']} not ready")
                    ent[4] += 1  # sweep must not reap mid-write
            if ent is None:
                raise ValueError(f"no push in progress for {msg['obj']}")
            try:
                data = msg["data"]
                off = msg["offset"]
                ent[0].buf[off:off + len(data)] = data
            finally:
                with self._lock:
                    # TCP orders a connection's chunks, so the high
                    # water mark equals contiguous bytes received.
                    ent[2] = max(ent[2], off + len(data))
                    ent[3] = time.monotonic()
                    ent[4] -= 1
            return {"ok": True}
        if op == "push_end":
            oid = ObjectID.from_hex(msg["obj"])
            with self._lock:
                ent = self._incoming.get(msg["obj"])
                if ent is not None and ent[2] == ent[1]:
                    del self._incoming[msg["obj"]]
            if ent is None:
                # A concurrent duplicate push already finalized it.
                return {"ok": True} if self.store.contains(oid) \
                    else {"error": "no push in progress"}
            if ent[2] != ent[1]:
                # Short stream: drop the partial allocation (under the
                # lock the entry stays for a restarted sender; this
                # sender's stream simply failed).
                return {"error": f"short push: {ent[2]}/{ent[1]} bytes"}
            self.store.seal(oid)
            # Register the replica so a cluster-wide free deletes this
            # copy too (same contract as pull-side caching).
            try:
                self.head.send({"op": "object_replica",
                                "obj": msg["obj"]})
            except Exception:
                pass
            return {"ok": True}
        if op == "pull_object":
            # Single-flight remote fetch into this node's arena on
            # behalf of a local worker ({obj, size, addr}).  Runs on a
            # side thread via Deferred so slow transfers never
            # head-of-line block this connection's other ops.
            obj_hex, size = msg["obj"], msg["size"]
            addr = msg.get("addr", "")
            d = rpc.Deferred()

            def _pull():
                oid = ObjectID.from_hex(obj_hex)

                def _do():
                    if self.store.contains(oid):
                        return True
                    client = (self._peer_conn(addr) if addr
                              else self.head)
                    _, cached = object_plane.pull_into_store(
                        client, self.store, obj_hex, size,
                        self.config.transfer_chunk_bytes,
                        window=self.config.pull_window, timeout=120.0)
                    if cached:
                        try:
                            self.head.send({"op": "object_replica",
                                            "obj": obj_hex})
                        except Exception:  # raylint: allow-swallow(replica hint is advisory; head rediscovers on demand)
                            pass
                    return cached

                try:
                    cached = self._pull_mgr.pull(obj_hex, _do,
                                                 timeout=150.0)
                    d.resolve({"ok": True, "cached": bool(cached)})
                except BaseException as e:  # noqa: BLE001
                    d.reject(e)

            threading.Thread(target=_pull, daemon=True,
                             name="nm-pull").start()
            return d
        if op == "cluster_view":
            with self._lock:
                return {"seq": self._view_seq, "at": self._view_at,
                        "nodes": self._view}
        if op == "available_resources":
            # Node-local answer from the synced view (no head hop).
            with self._lock:
                nodes = self._view
            out: Dict[str, float] = {}
            for n in nodes.values():
                if n.get("alive"):
                    for k, v in n["available"].items():
                        out[k] = out.get(k, 0.0) + v
            return out
        if op == "cluster_resources":
            with self._lock:
                nodes = self._view
            out = {}
            for n in nodes.values():
                if n.get("alive"):
                    for k, v in n["total"].items():
                        out[k] = out.get(k, 0.0) + v
            return out
        if op == "worker_alive":
            with self._lock:
                proc = self._procs.get(msg["worker_hex"])
            return proc is not None and proc.poll() is None
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown node op {op}")

    def _peer_conn(self, addr: str) -> rpc.Client:
        """Cached connection to another node's object server."""
        with self._peer_lock:
            c = self._peer_conns.get(addr)
        if c is not None and not c._closed:
            return c
        c = rpc.Client(addr, connect_timeout=5.0)
        with self._peer_lock:
            existing = self._peer_conns.get(addr)
            if existing is not None and not existing._closed:
                c.close()
                return existing
            self._peer_conns[addr] = c
        return c

    # -- lifecycle ------------------------------------------------------
    def _sweep_loop(self):
        """Reap exited worker processes and drop their arena pins; age
        out abandoned push-broadcast receptions; report host stats to
        the head on an interval (dashboard/reporter.py — the per-node
        reporter agent role)."""
        from ray_tpu.dashboard.reporter import HostStatsSampler

        sampler = HostStatsSampler()
        last_report = 0.0
        while not self._stopped.wait(1.0):
            if time.monotonic() - last_report >= 5.0:
                last_report = time.monotonic()
                try:
                    with self._lock:
                        nw = len(self._procs)
                    self.head.send({
                        "op": "node_stats",
                        "stats": sampler.sample(store=self.store,
                                                num_workers=nw)})
                except Exception:
                    pass
            stale = []
            with self._lock:
                for hex_, p in list(self._procs.items()):
                    if p.poll() is not None:
                        del self._procs[hex_]
                alive = [p.pid for p in self._procs.values()]
                now = time.monotonic()
                for obj_hex, ent in list(self._incoming.items()):
                    # Reap only senders that are provably gone: a long
                    # idle window (budget-contended broadcasts can gap
                    # minutes between chunks) AND no write in progress
                    # (deleting the segment under an active write would
                    # free an arena block mid-memcpy).
                    if now - ent[3] > 300.0 and ent[4] == 0:
                        del self._incoming[obj_hex]
                        stale.append(obj_hex)
            for obj_hex in stale:
                try:
                    self.store.delete(ObjectID.from_hex(obj_hex))
                except Exception:
                    pass
            alive.append(os.getpid())
            try:
                self.store.sweep(alive)
            except Exception:
                pass
            # The head going away (without a clean exit push): try to
            # redial — a restarted head accepts node re-registration
            # (gcs.py _op_register_node revival).  Only give up (and
            # reap the workers) when the reconnect window expires.
            if self.head._closed and not self._reconnect_head():
                self._stopped.set()

    def _reconnect_head(self) -> bool:
        timeout = self.config.gcs_reconnect_timeout_s
        if timeout <= 0:
            return False
        deadline = time.monotonic() + timeout
        self._register_msg["node_id"] = self.node_id  # keep identity
        while not self._stopped.is_set() and time.monotonic() < deadline:
            try:
                head = rpc.Client(self.head_address, on_push=self._on_push,
                                  connect_timeout=1.0)
                head.call(self._register_msg, timeout=10.0)
            except Exception:
                time.sleep(0.5)
                continue
            self.head = head
            return True
        return False

    def run_forever(self):
        try:
            self._stopped.wait()
        except KeyboardInterrupt:
            pass
        self.shutdown()

    def shutdown(self):
        self._stopped.set()
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        # Event-driven reap: each wait() blocks in the kernel until
        # that child exits or the shared deadline budget runs out — no
        # poll/sleep spin (late children are still killed below).
        deadline = time.monotonic() + 1.0
        still = []
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.001))
            except subprocess.TimeoutExpired:
                still.append(p)
        procs = still
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
        try:
            self.server.stop()
        except Exception:
            pass
        try:
            self.head.close()
        except Exception:
            pass
        self.store.cleanup()


def main(argv=None) -> int:
    import argparse

    from ray_tpu.core import knobs

    knobs.apply_interpreter_tuning()
    p = argparse.ArgumentParser("ray_tpu.core.node_manager")
    p.add_argument("--address", required=True, help="head control address")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--node-id", default="")
    p.add_argument("--label", action="append", default=[],
                   help="k=v node label (repeatable)")
    args = p.parse_args(argv)
    labels = dict(kv.split("=", 1) for kv in args.label)
    nm = NodeManager(args.address, num_cpus=args.num_cpus,
                     num_tpus=args.num_tpus, node_id=args.node_id,
                     labels=labels)
    print(f"node {nm.node_id} joined {args.address} "
          f"(object server {nm.server.address})", flush=True)
    nm.run_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
