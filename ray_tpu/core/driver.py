"""Head/driver runtime: starts the control plane in-process and connects.

Counterpart of ray.init()'s head path (python/ray/_private/worker.py:1225 +
node.py start_head_processes): here the control server runs as threads in
the driver process (one fewer process hop on a single host); worker
processes are spawned on demand by the scheduler.
"""

from __future__ import annotations

import atexit
import os
import time
import uuid
from typing import Optional

from ray_tpu.core.config import Config, get_config, reset_config
from ray_tpu.core.gcs import ControlServer
from ray_tpu.core.ids import ObjectID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.resources import ResourceSet, node_resources_from_env
from ray_tpu.core.runtime import CoreClient, set_runtime


class DriverRuntime:
    def __init__(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[dict] = None,
                 _system_config: Optional[dict] = None,
                 namespace: str = "",
                 address: Optional[str] = None,
                 log_to_driver: bool = True,
                 thin: bool = False):
        """Head mode (default): start the control plane in-process.
        Connect mode (``address=``): attach this driver to an existing
        cluster's control server — counterpart of ray.init(address=...)
        joining a running GCS (worker.py:1225 connect-only path)."""
        reset_config()
        self.config: Config = get_config().apply_overrides(_system_config)
        if address:
            self.control = None
            control_addr = address
        else:
            session_id = uuid.uuid4().hex[:12]
            if self.config.gcs_store_path:
                # Restart path: adopt the journaled session so the shm
                # arena (still holding sealed objects) and session dir
                # are re-attached rather than recreated.
                from ray_tpu.core.store_client import peek_journal_key

                prev = peek_journal_key(self.config.gcs_store_path,
                                        "__meta__/session_id")
                if prev:
                    session_id = prev
            self.session_dir = os.path.join(
                "/tmp/ray_tpu", f"session-{session_id}")
            os.makedirs(self.session_dir, exist_ok=True)
            node_res = node_resources_from_env(num_cpus, num_tpus, resources)
            self.control = ControlServer(
                session_id, self.config, node_res, self.session_dir,
                namespace=namespace)
            control_addr = self.control.address
        self.core = CoreClient(
            control_addr, WorkerID.from_random().hex(),
            kind="driver", config=self.config, thin=thin)
        if address:
            self.session_dir = self.core.session_dir
        self.namespace = namespace
        # Worker stdout/stderr → driver console (reference log_monitor.py
        # behavior; see core/log_monitor.py).
        self.log_monitor = None
        if log_to_driver:
            from ray_tpu.core.log_monitor import LogMonitor
            self.log_monitor = LogMonitor(self.session_dir).start()
        self.is_initialized = True
        set_runtime(self)
        atexit.register(self._atexit)

    @property
    def address(self) -> str:
        return self.control.address if self.control is not None \
            else self.core.client.address

    def _atexit(self):
        try:
            self.shutdown()
        except Exception:
            pass

    # facade -----------------------------------------------------------
    def get(self, refs, timeout=None):
        return self.core.get(refs, timeout)

    def put(self, value):
        return self.core.put(value)

    def wait(self, refs, num_returns=1, timeout=None):
        return self.core.wait(refs, num_returns, timeout)

    def submit_task(self, *a, **kw):
        return self.core.submit_task(*a, **kw)

    def create_actor(self, *a, **kw):
        if not kw.get("namespace"):
            kw["namespace"] = self.namespace
        return self.core.create_actor(*a, **kw)

    def submit_actor_task(self, *a, **kw):
        return self.core.submit_actor_task(*a, **kw)

    def kill_actor(self, *a, **kw):
        return self.core.kill_actor(*a, **kw)

    def get_named_actor(self, name: str, namespace: str = ""):
        return self.core.get_named_actor(name, namespace or self.namespace)

    def subscribe_actor(self, *a, **kw):
        return self.core.subscribe_actor(*a, **kw)

    def wait_actor_alive(self, *a, **kw):
        return self.core.wait_actor_alive(*a, **kw)

    def on_ref_deleted(self, object_id: ObjectID):
        self.core.on_ref_deleted(object_id)

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        out: concurrent.futures.Future = concurrent.futures.Future()
        inner = self.core.object_future(ref.hex())

        def _chain(f):
            try:
                out.set_result(self.core._load_object(ref.hex(), f.result()))
            except BaseException as e:  # noqa: BLE001
                out.set_exception(e)

        inner.add_done_callback(_chain)
        return out

    def kv(self):
        return self.core.client

    # cluster info ------------------------------------------------------
    def cluster_resources(self):
        return self.core.client.call({"op": "cluster_resources"})

    def available_resources(self):
        return self.core.client.call({"op": "available_resources"})

    def state_list(self, kind: str):
        return self.core.client.call({"op": f"list_{kind}"})

    def shutdown(self):
        if not getattr(self, "is_initialized", False):
            return
        self.is_initialized = False
        set_runtime(None)
        if self.log_monitor is not None:
            try:
                self.log_monitor.stop()
            except Exception:
                pass
        try:
            from ray_tpu.util.usage_stats import write_usage_report
            write_usage_report(self.session_dir)
        except Exception:
            pass
        try:
            self.core.close()
        except Exception:
            pass
        if self.control is not None:
            self.control.stop()
