"""Public API: init / shutdown / remote / get / put / wait / actors.

Counterpart of python/ray/_private/worker.py's public functions
(ray.init :1225, ray.get :2576, ray.put :2691, ray.wait :2756,
ray.remote :3149, ray.get_actor :2902).
"""

from __future__ import annotations

import inspect
from typing import Any, List, Optional, Sequence, Union

from ray_tpu.core import runtime as _runtime_mod
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.actor import method as method  # noqa: PLC0414 re-export
from ray_tpu.core.driver import DriverRuntime
from ray_tpu.core.exceptions import RayTpuError
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction


def init(num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[dict] = None,
         namespace: str = "",
         address: Optional[str] = None,
         ignore_reinit_error: bool = True,
         log_to_driver: bool = True,
         logging_config=None,
         _system_config: Optional[dict] = None) -> DriverRuntime:
    """Start the single-host runtime (control plane + worker pool), or —
    with ``address=`` — connect this driver to a running cluster
    ("auto" resolves the address file written by ``ray-tpu start``).

    logging_config: a LoggingConfig applied to this driver and inherited
    by workers this process spawns (core/logging_config.py).  In connect
    mode (address=...) remote workers are spawned by the cluster's own
    daemons and keep the config the cluster was started with."""
    from ray_tpu.core import knobs as _knobs

    _knobs.apply_interpreter_tuning()
    rt = _runtime_mod._global_runtime
    if rt is not None and getattr(rt, "is_initialized", False):
        if ignore_reinit_error:
            if logging_config is not None:
                import logging as _logging

                _logging.getLogger(__name__).warning(
                    "init(logging_config=...) ignored: runtime already "
                    "initialized (call shutdown() first)")
            return rt
        raise RayTpuError("ray_tpu.init() called twice")
    if address == "auto":
        address = _resolve_cluster_address()
    if logging_config is not None:
        from ray_tpu.core import logging_config as _lc

        if address:
            import logging as _logging

            _logging.getLogger(__name__).warning(
                "logging_config applies to this driver only: cluster "
                "daemons at %s spawn workers with their own environment",
                address)
        _lc.apply(logging_config)
        _lc.export_to_env(logging_config)
        global _logging_config_exported
        _logging_config_exported = True
    return DriverRuntime(
        num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
        namespace=namespace, address=address,
        log_to_driver=log_to_driver,
        _system_config=_system_config)


_ADDRESS_FILE = "/tmp/ray_tpu/cluster_address"


def _resolve_cluster_address() -> str:
    import os

    env = os.environ.get("RAY_TPU_ADDRESS")
    if env and env != "auto":
        return env
    try:
        with open(_ADDRESS_FILE) as f:
            return f.read().strip()
    except FileNotFoundError:
        raise RayTpuError(
            "address='auto' but no running cluster found (no "
            f"RAY_TPU_ADDRESS env var and no {_ADDRESS_FILE}); start one "
            "with `ray-tpu start --head`") from None


def is_initialized() -> bool:
    rt = _runtime_mod._global_runtime
    return rt is not None and getattr(rt, "is_initialized", False)


_logging_config_exported = False


def shutdown():
    rt = _runtime_mod._global_runtime
    if rt is not None and hasattr(rt, "shutdown"):
        rt.shutdown()
    # Session config must not leak into the next init — but only pop what
    # init() itself exported (a user-exported variable is theirs to keep).
    global _logging_config_exported
    if _logging_config_exported:
        from ray_tpu.core import logging_config as _lc

        _lc.export_to_env(None)
        _logging_config_exported = False


def remote(*args, **kwargs):
    """Decorator: @remote or @remote(num_cpus=..., num_tpus=..., ...)."""

    def make(obj):
        if inspect.isclass(obj):
            valid = {"num_cpus", "num_tpus", "resources", "max_restarts",
                     "max_task_retries",
                     "max_concurrency", "concurrency_groups", "name",
                     "namespace", "lifetime", "runtime_env",
                     "scheduling_strategy"}
            opts = {k: v for k, v in kwargs.items() if k in valid}
            return ActorClass(obj, **opts)
        valid = {"num_returns", "num_cpus", "num_tpus", "resources",
                 "max_retries", "runtime_env", "scheduling_strategy"}
        opts = {k: v for k, v in kwargs.items() if k in valid}
        return RemoteFunction(obj, **opts)

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return make(args[0])
    if args:
        raise TypeError("@remote takes only keyword arguments")
    return make


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        timeout: Optional[float] = None):
    rt = _runtime_mod.get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects ObjectRef or list, got {type(refs)}")
    return rt.get(list(refs), timeout)


def put(value: Any) -> ObjectRef:
    return _runtime_mod.get_runtime().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return _runtime_mod.get_runtime().wait(
        list(refs), num_returns=num_returns, timeout=timeout)


def get_runtime_context():
    """Identity/context of the current process (reference:
    ray.get_runtime_context(), python/ray/runtime_context.py)."""
    from ray_tpu.core.runtime_context import get_runtime_context as _grc

    return _grc()


def register_named_function(name: str, fn) -> str:
    """Register a Python function for cross-language invocation (the
    reference's FunctionDescriptor story): C++ clients submit it by name
    via submit_named_task (see cpp/). Returns the function id."""
    import cloudpickle

    from ray_tpu.core.runtime import func_content_id

    rt = _runtime_mod.get_runtime()
    blob = cloudpickle.dumps(fn)
    func_id = func_content_id(blob)
    rt.core.ensure_func(func_id, blob)
    rt.kv().call({"op": "kv_put", "key": f"__named_fn__/{name}",
                  "value": func_id.encode(), "overwrite": True})
    return func_id


def cancel(ref: ObjectRef, *, force: bool = False) -> bool:
    """Cancel the task producing ``ref``.  Pending tasks are always
    cancellable; running tasks only with force=True (worker is killed)."""
    rt = _runtime_mod.get_runtime()
    core = getattr(rt, "core", None)
    if core is not None:
        # Owner-side first: lease-path tasks never reached the head
        # (reference: cancellation is owner-initiated, CancelTask
        # core_worker.proto:441).
        return core.cancel_ref(ref.hex(), force=force)
    return bool(rt.kv().call(
        {"op": "cancel_object", "obj": ref.hex(), "force": force}))


def kill(actor: ActorHandle, *, no_restart: bool = True):
    _runtime_mod.get_runtime().kill_actor(
        actor._actor_hex, no_restart=no_restart)


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    info = _runtime_mod.get_runtime().get_named_actor(name, namespace)
    if info is None:
        raise ValueError(f"Failed to look up actor {name!r}")
    return ActorHandle(info["actor"], info["class_id"].split(":")[0])


def cluster_resources() -> dict:
    return _runtime_mod.get_runtime().cluster_resources()


def available_resources() -> dict:
    return _runtime_mod.get_runtime().available_resources()


def nodes() -> list:
    """Cluster membership with resources and liveness (counterpart of
    ray.nodes(), python/ray/_private/worker.py; served from the state
    API's node table — on workers, from the locally synced view)."""
    return _runtime_mod.get_runtime().state_list("nodes")


def timeline(filename=None):
    """Chrome-trace dump of task state transitions (counterpart of
    ray.timeline(), python/ray/_private/state.py:434).  Returns the
    event list; with ``filename`` also writes chrome://tracing JSON."""
    from ray_tpu.util.timeline import timeline as _timeline

    return _timeline(filename)


def get_accelerator_ids() -> dict:
    """Accelerator ids assigned to this worker, keyed by resource name
    (counterpart of ray.get_runtime_context().get_accelerator_ids();
    same TPU_VISIBLE_CHIPS/RAY_TPU_CHIPS parsing the scheduler's chip
    detection uses — core/resources.py)."""
    from ray_tpu.core.resources import visible_tpu_chip_ids

    ids = visible_tpu_chip_ids()
    return {"TPU": ids if ids is not None else []}


def get_gpu_ids() -> list:
    """Compat shim for ray.get_gpu_ids(): this framework schedules TPUs
    (see get_accelerator_ids); GPU ids are always empty."""
    return []


def client(address: str = "auto"):
    """Thin-client connection builder (counterpart of ray.client() /
    ClientBuilder, python/ray/client_builder.py): returns a context
    whose ``connect()``/``disconnect()`` manage a TCP-only runtime."""
    from ray_tpu.util import client as _client

    class _Builder:
        def __init__(self, addr):
            self._addr = addr

        def connect(self):
            return _client.connect(self._addr)

    return _Builder(address)
