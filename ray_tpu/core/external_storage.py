"""External storage for spilled objects.

Counterpart of the reference's python/ray/_private/external_storage.py
(ExternalStorage ABC :72, FileSystemStorage :246, smart_open/S3 :451) —
the sink the raylet's LocalObjectManager spills cold primary copies to
(src/ray/raylet/local_object_manager.h:105). Here the control server
spills directly (core/gcs.py _maybe_spill) since it owns the store.

URIs are `spill:<backend>:<key>`; backends implement raw put/get/delete
of bytes.
"""

from __future__ import annotations

import os
from typing import Optional


class ExternalStorage:
    name = "external"

    def spill(self, key: str, data: bytes) -> str:
        """Persist bytes; returns a restore URI."""
        raise NotImplementedError

    def restore(self, uri: str) -> bytes:
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """Spill to a local directory (reference FileSystemStorage)."""

    name = "filesystem"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, key)

    def spill(self, key: str, data: bytes) -> str:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.rename(tmp, self._path(key))  # atomic publish
        return f"spill:filesystem:{key}"

    def restore(self, uri: str) -> bytes:
        key = uri.rsplit(":", 1)[1]
        with open(self._path(key), "rb") as f:
            return f.read()

    def delete(self, uri: str) -> None:
        key = uri.rsplit(":", 1)[1]
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass


class SmartOpenStorage(ExternalStorage):
    """Remote-URI spilling via smart_open (reference :451 — S3/GS/...).
    Gated: raises a clear error if smart_open isn't baked into the
    image."""

    name = "smart_open"

    def __init__(self, uri_prefix: str):
        try:
            import smart_open  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "smart_open is not available in this image; use "
                "FileSystemStorage or bake smart_open in") from e
        self.uri_prefix = uri_prefix.rstrip("/")

    def spill(self, key: str, data: bytes) -> str:
        from smart_open import open as s_open

        uri = f"{self.uri_prefix}/{key}"
        with s_open(uri, "wb") as f:
            f.write(data)
        return f"spill:smart_open:{uri}"

    def restore(self, uri: str) -> bytes:
        from smart_open import open as s_open

        with s_open(uri.split(":", 2)[2], "rb") as f:
            return f.read()

    def delete(self, uri: str) -> None:
        pass  # remote GC is offline (reference leaves this to lifecycle)


def storage_from_spec(spec: Optional[str], session_dir: str
                      ) -> ExternalStorage:
    """spec: None/'' → session-local dir; a path → that dir; an
    s3://... prefix → smart_open."""
    if not spec:
        return FileSystemStorage(os.path.join(session_dir, "spilled"))
    if "://" in spec:
        return SmartOpenStorage(spec)
    return FileSystemStorage(spec)
