"""Core runtime (tasks/actors/objects/control plane)."""
