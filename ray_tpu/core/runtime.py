"""Per-process core runtime: the counterpart of the reference's CoreWorker.

Every participating process (driver or worker) holds a CoreClient that talks
to the control server (gcs.py): object subscription/resolution, task and
actor submission, reference counting, and the shared-memory store attachment.
Reference call-stack parity: CoreWorker::SubmitTask / Put / Get
(src/ray/core_worker/core_worker.cc:2166/:1241/:1552) and the direct actor
transport (transport/direct_actor_task_submitter.cc — per-handle ordered
submission over a dedicated connection).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import cloudpickle

from ray_tpu.core import rpc, serialization
from ray_tpu.core.config import Config, get_config
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskError,
)
from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.task_spec import ActorCreationSpec, TaskArg, TaskSpec

_global_runtime = None
_runtime_lock = threading.Lock()


def _is_missing_segment_error(e: Exception) -> bool:
    """True for attach failures meaning "no longer at that location"
    (deleted arena slot / unlinked file) as opposed to real IO faults."""
    if isinstance(e, FileNotFoundError):
        return True
    try:
        from ray_tpu.native.store import ArenaError

        return isinstance(e, ArenaError)
    except ImportError:
        return False


def dump_all_stacks() -> str:
    """Format every thread's current Python stack (the in-process
    counterpart of the reference's py-spy `ray stack` dumps — no
    external profiler binary needed for cooperative processes)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        out.append(f"--- Thread {tid} ({names.get(tid, '?')}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


def get_runtime():
    if _global_runtime is None:
        raise RuntimeError(
            "ray_tpu not initialized; call ray_tpu.init() first")
    return _global_runtime


def set_runtime(rt):
    global _global_runtime
    with _runtime_lock:
        _global_runtime = rt


class CoreClient:
    """Client-side core: object futures, submission, refcounting."""

    def __init__(self, control_addr: str, worker_hex: str, kind: str,
                 address: str = "", env_key: str = "",
                 config: Optional[Config] = None, thin: bool = False):
        self.worker_hex = worker_hex
        self.kind = kind
        self.config = config or get_config()
        # Set BEFORE any rpc.Client exists: its reader thread can fire
        # _on_control_disconnect mid-__init__ (head dying in the
        # registration window), which dereferences these.
        self._closed = False
        self._reconnecting = threading.Lock()
        # Thin mode (reference Ray Client, util/client/): no shared-memory
        # attachment — every payload rides the TCP connection, so the
        # client can live on any machine that reaches the control address.
        self.thin = thin
        # Hooks must exist before the rpc recv thread can deliver pushes.
        self.on_execute_task = None
        self.on_create_actor = None
        self.on_exit = None
        # Fired after a successful control-plane reconnect (head restart
        # tolerance): workers re-announce themselves here.
        self.on_reconnect = None
        self.control_addr = control_addr
        self._register_msg = {
            "op": "register",
            "worker_hex": worker_hex,
            "pid": os.getpid(),
            "kind": kind,
            "address": address,
            "env_key": env_key,
            "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
        }
        self.client = rpc.Client(control_addr, on_push=self._on_push,
                                 on_disconnect=self._on_control_disconnect)
        reply = self.client.call(self._register_msg)
        self.session_id = reply["session_id"]
        self.session_dir = reply["session_dir"]
        # The arena this process attaches is its NODE's (multi-host:
        # each node manager owns one; head + logical nodes share the
        # head's — gcs.py _op_register decides).
        self.store_node = reply.get("store_node", "head")
        self.store = None if thin else ShmObjectStore(
            reply.get("store_key") or self.session_id, reply["shm_dir"])

        self._lock = threading.Lock()
        # Thread-local put buffering: a worker executing a task batches
        # its result put_object messages into the task_done message (one
        # control round instead of N+1) — see worker.py _execute.
        self._tls = threading.local()
        self._object_futures: Dict[str, Future] = {}
        self._subscribed: set[str] = set()
        # actor state tracking
        self._actor_state: Dict[str, dict] = {}
        self._actor_cv = threading.Condition()
        self._actor_conns: Dict[str, rpc.Client] = {}
        # Connections to other nodes' object servers (cross-node pulls).
        self._node_conns: Dict[str, rpc.Client] = {}
        self._actor_queues: Dict[str, List[TaskSpec]] = {}
        self._sent_funcs: set[str] = set()

    # ------------------------------------------------------------------
    # Control-plane reconnection (reference: raylet/worker redial after
    # GCS restart, NotifyGCSRestart node_manager.proto:383).
    def _on_control_disconnect(self):
        if self._closed:
            return
        if self.config.gcs_reconnect_timeout_s <= 0:
            if self.on_exit is not None:
                self.on_exit()
            return
        # One loop at a time: a flapping head must not stack concurrent
        # reconnectors racing writes to self.client.
        if not self._reconnecting.acquire(blocking=False):
            return
        threading.Thread(target=self._reconnect_loop,
                         name="control-reconnect", daemon=True).start()

    def _reconnect_loop(self):
        try:
            self._reconnect_loop_inner()
        finally:
            self._reconnecting.release()
        # A drop during the adoption/resync window fires the callback
        # while _reconnecting is still held (swallowed by the
        # non-blocking acquire) — recheck now that it's released.
        client = self.client
        if not self._closed and getattr(client, "_closed", False):
            self._on_control_disconnect()

    def _reconnect_loop_inner(self):
        deadline = time.monotonic() + self.config.gcs_reconnect_timeout_s
        delay = 0.2
        while not self._closed and time.monotonic() < deadline:
            client = None
            try:
                # No on_disconnect on the probe: a flap during resync
                # must not spawn a second loop; the callback is attached
                # only once this client is adopted.
                client = rpc.Client(
                    self.control_addr, on_push=self._on_push,
                    connect_timeout=1.0)
                client.call(self._register_msg, timeout=10.0)
                # Re-subscribe everything unresolved.  grace=True: the
                # restarted head fails objects nobody re-produces within
                # its grace window instead of leaving gets hanging.
                with self._lock:
                    pending = [
                        h for h in self._subscribed
                        if (f := self._object_futures.get(h)) is not None
                        and not f.done()]
                with self._actor_cv:
                    actors = set(self._actor_state) | \
                        set(self._actor_queues)
                if pending:
                    client.send({"op": "subscribe_objects",
                                 "objs": pending, "grace": True})
                for actor_hex in actors:
                    client.send({"op": "subscribe_actor",
                                 "actor": actor_hex})
            except Exception:
                if client is not None:
                    client.close()
                time.sleep(delay)
                delay = min(delay * 1.7, 2.0)
                continue
            client._on_disconnect = self._on_control_disconnect
            if client._closed:
                # Dropped between resync and adoption: the callback we
                # just attached never fires for that earlier loss.
                client.close()
                time.sleep(delay)
                continue
            self.client = client
            cb = self.on_reconnect
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass
            return
        # Could not reach a head within the window: give up the same way
        # a worker death would.
        if self.on_exit is not None:
            self.on_exit()

    def _on_push(self, msg: dict):
        op = msg.get("op")
        if op == "object_ready":
            with self._lock:
                fut = self._object_futures.get(msg["obj"])
            if fut is not None and not fut.done():
                fut.set_result(msg)
        elif op == "actor_update":
            self._handle_actor_update(msg)
        elif op == "execute_task" and self.on_execute_task is not None:
            self.on_execute_task(msg["spec"])
        elif op == "create_actor_instance" and self.on_create_actor is not None:
            self.on_create_actor(msg["spec"])
        elif op == "profile":
            # On-demand profiling (gcs.py _op_profile_worker): run off
            # the push thread; the worker keeps executing its task.
            threading.Thread(target=self._run_profile, args=(msg,),
                             name="profile", daemon=True).start()
        elif op == "exit" and self.on_exit is not None:
            self.on_exit()

    def _run_profile(self, msg: dict):
        kind = msg.get("kind", "stack")
        try:
            if kind == "stack":
                data = dump_all_stacks()
            elif kind == "jax_trace":
                import time as _time

                import jax

                out_dir = os.path.join(
                    self.session_dir, "profiles",
                    f"{self.worker_hex[:8]}-{int(_time.time())}")
                os.makedirs(out_dir, exist_ok=True)
                # Process-wide xplane trace: captures any jitted work the
                # task threads run during the window (viewable with
                # tensorboard / xprof).
                with jax.profiler.trace(out_dir):
                    _time.sleep(float(msg.get("duration_s", 2.0)))
                data = out_dir
            else:
                data = f"unknown profile kind {kind!r}"
        except Exception as e:  # noqa: BLE001
            data = f"profile failed: {type(e).__name__}: {e}"
        if "_local_result" in msg:  # self-profile (state/api.py)
            msg["_local_result"]["data"] = data
            return
        try:
            self.client.send({"op": "profile_result",
                              "token": msg.get("token"), "data": data})
        except Exception:
            pass

    def _handle_actor_update(self, msg: dict):
        actor_hex = msg["actor"]
        with self._actor_cv:
            self._actor_state[actor_hex] = msg
            self._actor_cv.notify_all()
        if msg["state"] == "ALIVE":
            self._flush_actor_queue(actor_hex, msg["address"])
        elif msg["state"] == "DEAD":
            self._fail_actor_queue(actor_hex, msg.get("reason", ""))

    # ------------------------------------------------------------------
    # Objects
    def object_future(self, obj_hex: str) -> Future:
        return self.object_futures([obj_hex])[0]

    def object_futures(self, obj_hexes: Sequence[str]) -> List[Future]:
        """Batch variant: ONE subscribe message for all new hexes (a
        get() of N refs used to cost N control messages)."""
        futs: List[Future] = []
        new: List[str] = []
        with self._lock:
            for obj_hex in obj_hexes:
                fut = self._object_futures.get(obj_hex)
                if fut is None:
                    fut = Future()
                    self._object_futures[obj_hex] = fut
                futs.append(fut)
                if obj_hex not in self._subscribed:
                    self._subscribed.add(obj_hex)
                    new.append(obj_hex)
            if new:
                self.client.send({"op": "subscribe_objects", "objs": new})
        return futs

    def _load_object(self, obj_hex: str, info: dict,
                     timeout: Optional[float] = None,
                     _retried: bool = False) -> Any:
        if info.get("inline") is not None:
            data = info["inline"]
        elif info.get("in_shm"):
            if self.store is None:
                # Thin client: the server reads the shm payload for us.
                # with_meta: the error flag must come from the same
                # snapshot as the payload — the object may have become an
                # ObjectLostError after this client cached `info`.
                reply = self.client.call({"op": "fetch_object",
                                          "obj": obj_hex,
                                          "with_meta": True})
                if reply is None or reply.get("data") is None:
                    raise RuntimeError(
                        f"object {obj_hex} no longer available")
                return self._finish_load(
                    obj_hex, reply["data"],
                    {**info, "is_error": reply["is_error"]})
            try:
                seg = self.store.attach(ObjectID.from_hex(obj_hex),
                                        info["size"])
            except Exception as e:  # noqa: BLE001
                if info.get("node", "head") != self.store_node:
                    # Not in this node's arena (and no cached replica):
                    # pull the bytes from the holding node over the
                    # object plane (reference ObjectManager Pull,
                    # object_manager.h:139) and cache them locally.
                    try:
                        data = self._pull_remote_object(obj_hex, info)
                        return self._finish_load(obj_hex, data, info)
                    except Exception:
                        if _retried:
                            raise
                        # Node dead or its arena evicted the copy: tell
                        # the head (it verifies and kicks lineage
                        # reconstruction), then re-subscribe for the
                        # recovered value.
                        try:
                            self.client.call(
                                {"op": "report_object_lost",
                                 "obj": obj_hex}, timeout=30.0)
                        except Exception:
                            pass
                        e = FileNotFoundError(obj_hex)
                # Stale location: the server may have SPILLED the object
                # after this client cached its in-shm info. Drop the
                # cached future + subscription and re-subscribe — the
                # server restores spilled objects on subscribe.
                if _retried or not _is_missing_segment_error(e):
                    raise
                fut = self._refetch_object(obj_hex)
                try:
                    # Honor an explicit caller timeout fully; for
                    # timeout=None gets, bound the wait generously (a
                    # truly freed object's fresh subscription would stay
                    # PENDING forever, but slow external-storage restores
                    # must be allowed to finish).
                    info2 = fut.result(
                        timeout=timeout if timeout is not None else 300.0)
                except TimeoutError:
                    raise GetTimeoutError(
                        f"timed out refetching {obj_hex}") from None
                return self._load_object(obj_hex, info2, _retried=True)
            data = seg.buf[: info["size"]]
        else:
            raise RuntimeError(f"object {obj_hex} ready but has no payload")
        return self._finish_load(obj_hex, data, info)

    def _finish_load(self, obj_hex: str, data, info: dict) -> Any:
        value = serialization.deserialize(data, ref_deserializer=self._on_ref_deser)
        if info.get("is_error"):
            raise value
        return value

    def _node_conn(self, address: str) -> rpc.Client:
        """Connection to another node's object server (cached).  The dial
        happens OUTSIDE self._lock — a dead node's connect retries must
        not stall this process's object subscription path."""
        with self._lock:
            conn = self._node_conns.get(address)
        if conn is not None and not conn._closed:
            return conn
        conn = rpc.Client(address, connect_timeout=5.0)
        with self._lock:
            existing = self._node_conns.get(address)
            if existing is not None and not existing._closed:
                conn.close()
                return existing
            self._node_conns[address] = conn
        return conn

    def _pull_remote_object(self, obj_hex: str, info: dict) -> bytes:
        """Chunked pull of an object living in another node's arena
        (reference ObjectManager chunked transfer via object_buffer_pool).
        addr == "" means the head arena: chunks ride the control client.
        The bytes are cached into the local arena so later readers on
        this node hit shm (the reference PullManager materializes pulled
        chunks into local plasma the same way)."""
        size = info["size"]
        addr = info.get("addr", "")
        client = self._node_conn(addr) if addr else self.client
        payload = rpc.pull_object_chunked(
            client, obj_hex, size, self.config.transfer_chunk_bytes,
            timeout=120.0)
        try:
            oid = ObjectID.from_hex(obj_hex)
            seg = self.store.create(oid, size)
            seg.buf[:size] = payload
            self.store.seal(oid)
            # Tell the directory about the replica so a cluster-wide free
            # deletes this arena's copy too (no leak on consumer nodes).
            self.client.send({"op": "object_replica", "obj": obj_hex})
        except Exception:  # cache is best-effort (arena full, race)
            pass
        return payload

    def forget_object(self, obj_hex: str):
        """Retire a speculative subscription (a stream-item probe for an
        index the stream ended before): drop the local future and tell
        the directory to delete the PENDING placeholder if nothing else
        references it — otherwise every consumed stream leaks one
        entry on the head and one future here."""
        with self._lock:
            self._object_futures.pop(obj_hex, None)
            self._subscribed.discard(obj_hex)
        try:
            self.client.send({"op": "forget_object", "obj": obj_hex})
        except Exception:
            pass

    def _refetch_object(self, obj_hex: str) -> Future:
        """Forget the resolved location of an object and subscribe again
        (used when a cached in-shm location went stale via spilling)."""
        with self._lock:
            self._object_futures.pop(obj_hex, None)
            self._subscribed.discard(obj_hex)
        return self.object_future(obj_hex)

    def _on_ref_deser(self, ref: ObjectRef):
        # A ref arrived inside a deserialized value: register a borrow so the
        # owner keeps the object alive while this process holds the ref
        # (reference borrowing protocol, reference_count.h).
        try:
            self.client.send({"op": "incref", "obj": ref.hex()})
        except Exception:
            pass

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None):
        futs = self.object_futures([r.hex() for r in refs])
        deadline = None if timeout is None else time.monotonic() + timeout
        results = []
        for r, fut in zip(refs, futs):
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(f"get() timed out on {r}")
            try:
                info = fut.result(timeout=remaining)
            except TimeoutError:
                raise GetTimeoutError(f"get() timed out on {r}") from None
            remaining = None if deadline is None \
                else max(deadline - time.monotonic(), 0.1)
            results.append(self._load_object(r.hex(), info,
                                             timeout=remaining))
        return results

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        self._store_value(oid, value)
        return ObjectRef(oid, owner=self.worker_hex)

    def _store_value(self, oid: ObjectID, value: Any, is_error: bool = False):
        ser = serialization.serialize(value)
        size = ser.total_bytes
        # Thin clients ship everything inline over the connection (bounded
        # only by the rpc frame limit); full clients inline small objects
        # and put the rest in shm.
        if self.store is None:
            if size > self.config.rpc_max_message_bytes:
                raise ValueError(
                    f"object of {size} bytes exceeds the thin client's "
                    f"message limit ({self.config.rpc_max_message_bytes});"
                    " connect a full driver (ray_tpu.init(address=...)) "
                    "for shared-memory puts")
            inline_ok = True
        else:
            inline_ok = size <= self.config.max_inline_object_size
        if inline_ok:
            self._send_or_buffer({
                "op": "put_object", "obj": oid.hex(), "size": size,
                "inline": ser.to_bytes(), "is_error": is_error,
            })
        else:
            seg = self.store.create(oid, size)
            ser.write_into(seg.buf[:size])
            self.store.seal(oid)
            self._send_or_buffer({
                "op": "put_object", "obj": oid.hex(), "size": size,
                "inline": None, "in_shm": True, "is_error": is_error,
            })

    def _send_or_buffer(self, msg: dict):
        buf = getattr(self._tls, "put_buffer", None)
        if buf is not None:
            buf.append(msg)
        else:
            self.client.send(msg)

    def begin_put_batch(self):
        self._tls.put_buffer = []

    def take_put_batch(self) -> List[dict]:
        buf = getattr(self._tls, "put_buffer", None) or []
        self._tls.put_buffer = None
        return buf

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        futs = dict(zip(refs, self.object_futures(
            [r.hex() for r in refs])))
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        import concurrent.futures as cf

        pending = dict(futs)
        while len(ready) < num_returns and pending:
            remaining = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            done, _ = cf.wait(
                list(pending.values()), timeout=remaining,
                return_when=cf.FIRST_COMPLETED)
            if not done:
                break
            for r in list(pending):
                if pending[r].done():
                    ready.append(r)
                    del pending[r]
            if deadline is not None and time.monotonic() >= deadline:
                break
        ready = ready[:num_returns]
        ready_set = set(ready)
        not_ready = [r for r in refs if r not in ready_set]
        return ready, not_ready

    def on_ref_deleted(self, object_id: ObjectID):
        if self._closed:
            return
        try:
            self.client.send({"op": "decref", "obj": object_id.hex()})
        except Exception:
            pass

    # ------------------------------------------------------------------
    # Task submission
    def _prepare_args(self, args: Sequence[Any], borrows: List[str]):
        out: List[TaskArg] = []
        for a in args:
            if isinstance(a, ObjectRef):
                borrows.append(a.hex())
                self.client.send({"op": "incref", "obj": a.hex()})
                out.append(TaskArg(is_ref=True, object_hex=a.hex()))
            else:
                ser = serialization.serialize(a)
                for hex_id in ser.contained_refs:
                    borrows.append(hex_id)
                    self.client.send({"op": "incref", "obj": hex_id})
                if ser.total_bytes > self.config.max_inline_object_size:
                    ref = self.put(a)
                    borrows.append(ref.hex())
                    self.client.send({"op": "incref", "obj": ref.hex()})
                    out.append(TaskArg(is_ref=True, object_hex=ref.hex()))
                else:
                    out.append(TaskArg(is_ref=False, data=ser.to_bytes()))
        return out

    def ensure_func(self, func_id: str, blob: bytes) -> Optional[bytes]:
        """Upload the function blob once per session; return None if cached."""
        if func_id in self._sent_funcs:
            return None
        self.client.send({"op": "put_func", "func_id": func_id, "blob": blob})
        self._sent_funcs.add(func_id)
        return None

    def fetch_func(self, func_id: str) -> Optional[bytes]:
        return self.client.call({"op": "get_func", "func_id": func_id})

    def _prepare_runtime_env(self, runtime_env: Optional[dict]
                             ) -> Optional[dict]:
        """Package local working_dir/py_modules into content-addressed
        pkg:// KV uploads (runtime_env/packaging.py) so the env dict that
        ships — and keys the worker pool — is location-independent."""
        if not runtime_env:
            return runtime_env
        from ray_tpu.runtime_env.packaging import prepare_runtime_env

        return prepare_runtime_env(runtime_env, self.client.call)

    @staticmethod
    def _split_strategy(scheduling_strategy):
        """Extract (pg_hex, bundle_index, residual_strategy).

        PlacementGroupSchedulingStrategy becomes spec fields (the scheduler
        keys on them); other strategies ship as-is."""
        if scheduling_strategy is None:
            return "", -1, None
        if type(scheduling_strategy).__name__ == \
                "PlacementGroupSchedulingStrategy":
            pg = scheduling_strategy.placement_group
            return (pg._pg_hex,
                    scheduling_strategy.placement_group_bundle_index, None)
        return "", -1, scheduling_strategy

    def submit_task(self, func_id: str, func_blob: bytes, args: Sequence[Any],
                    num_returns, resources: Dict[str, float],
                    max_retries: int, name: str = "",
                    runtime_env: Optional[dict] = None,
                    scheduling_strategy=None):
        """Returns a list of ObjectRefs, or an ObjectRefGenerator when
        num_returns == "streaming" (core/streaming.py)."""
        from ray_tpu.core.streaming import STREAMING, ObjectRefGenerator

        streaming = num_returns == STREAMING
        borrows: List[str] = []
        task_args = self._prepare_args(args, borrows)
        self.ensure_func(func_id, func_blob)
        runtime_env = self._prepare_runtime_env(runtime_env)
        return_ids = [] if streaming else [
            ObjectID.from_random() for _ in range(num_returns)]
        pg_hex, bundle_index, scheduling_strategy = self._split_strategy(
            scheduling_strategy)
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            func_id=func_id,
            func_blob=None,
            args=task_args,
            num_returns=0 if streaming else num_returns,
            return_ids=return_ids,
            resources=resources,
            max_retries=max_retries,
            name=name,
            owner=self.worker_hex,
            runtime_env=runtime_env,
            scheduling_strategy=scheduling_strategy,
            placement_group_hex=pg_hex,
            bundle_index=bundle_index,
            borrows=borrows,
            is_streaming=streaming,
        )
        self.client.send({"op": "submit_task", "spec": spec})
        if streaming:
            return ObjectRefGenerator(spec.task_id)
        return [ObjectRef(oid, owner=self.worker_hex) for oid in return_ids]

    # ------------------------------------------------------------------
    # Actors
    def create_actor(self, class_id: str, class_blob: bytes,
                     args: Sequence[Any], resources: Dict[str, float],
                     max_restarts: int, name: str, namespace: str,
                     max_concurrency: int,
                     runtime_env: Optional[dict] = None,
                     scheduling_strategy=None) -> ActorID:
        borrows: List[str] = []
        task_args = self._prepare_args(args, borrows)
        self.ensure_func(class_id, class_blob)
        runtime_env = self._prepare_runtime_env(runtime_env)
        actor_id = ActorID.from_random()
        pg_hex, bundle_index, scheduling_strategy = self._split_strategy(
            scheduling_strategy)
        spec = ActorCreationSpec(
            actor_id=actor_id,
            class_id=class_id,
            class_blob=None,
            args=task_args,
            resources=resources,
            max_restarts=max_restarts,
            name=name,
            namespace=namespace,
            max_concurrency=max_concurrency,
            owner=self.worker_hex,
            runtime_env=runtime_env,
            scheduling_strategy=scheduling_strategy,
            placement_group_hex=pg_hex,
            bundle_index=bundle_index,
        )
        self.client.send({"op": "create_actor", "spec": spec})
        self.client.send({"op": "subscribe_actor", "actor": actor_id.hex()})
        with self._actor_cv:
            self._actor_queues.setdefault(actor_id.hex(), [])
        return actor_id

    def subscribe_actor(self, actor_hex: str):
        with self._actor_cv:
            if actor_hex not in self._actor_state:
                self.client.send({"op": "subscribe_actor", "actor": actor_hex})
                self._actor_queues.setdefault(actor_hex, [])

    def actor_state(self, actor_hex: str) -> Optional[dict]:
        with self._actor_cv:
            return self._actor_state.get(actor_hex)

    def wait_actor_alive(self, actor_hex: str, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._actor_cv:
            while True:
                st = self._actor_state.get(actor_hex)
                if st is not None and st["state"] in ("ALIVE", "DEAD"):
                    return st
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"actor {actor_hex} not alive in time")
                self._actor_cv.wait(timeout=remaining)

    def submit_actor_task(self, actor_hex: str, method_name: str,
                          args: Sequence[Any], num_returns,
                          name: str = ""):
        """num_returns may be "streaming": the method is a generator and
        each yield becomes its own object (core/streaming.py), returned
        as an ObjectRefGenerator — the streaming-response path serve's
        ingress uses for token streams."""
        from ray_tpu.core.streaming import (
            STREAMING,
            ObjectRefGenerator,
            stream_eos_id,
        )

        streaming = num_returns == STREAMING
        borrows: List[str] = []
        task_args = self._prepare_args(args, borrows)
        task_id = TaskID.from_random()
        return_ids = [] if streaming else [
            ObjectID.from_random() for _ in range(num_returns)]
        # Register returns under the actor so its death fails waiters;
        # for streams that role falls to the end-of-stream object.
        reg = [stream_eos_id(task_id).hex()] if streaming else \
            [oid.hex() for oid in return_ids]
        self.client.send({
            "op": "register_objects",
            "objs": reg,
            "actor": actor_hex,
        })
        spec = TaskSpec(
            task_id=task_id,
            func_id="", func_blob=None,
            args=task_args,
            num_returns=0 if streaming else num_returns,
            return_ids=return_ids,
            resources={},
            owner=self.worker_hex,
            actor_id=ActorID.from_hex(actor_hex),
            method_name=method_name,
            name=name or method_name,
            borrows=borrows,
            is_streaming=streaming,
        )
        self._route_actor_task(actor_hex, spec)
        if streaming:
            return ObjectRefGenerator(spec.task_id)
        return [ObjectRef(oid, owner=self.worker_hex) for oid in return_ids]

    def _route_actor_task(self, actor_hex: str, spec: TaskSpec):
        with self._actor_cv:
            st = self._actor_state.get(actor_hex)
            if st is None or st["state"] in ("PENDING_CREATION", "RESTARTING"):
                self._actor_queues.setdefault(actor_hex, []).append(spec)
                if st is None:
                    self.client.send(
                        {"op": "subscribe_actor", "actor": actor_hex})
                return
            if st["state"] == "DEAD":
                self._fail_actor_task(spec, st.get("reason", "actor dead"))
                return
            address = st["address"]
        self._send_actor_task(actor_hex, address, spec)

    def _actor_conn(self, address: str) -> rpc.Client:
        with self._lock:
            conn = self._actor_conns.get(address)
            if conn is None:
                conn = rpc.Client(address)
                self._actor_conns[address] = conn
            return conn

    def _send_actor_task(self, actor_hex: str, address: str, spec: TaskSpec):
        try:
            self._actor_conn(address).send({"op": "actor_task", "spec": spec})
        except Exception as e:  # connection refused: actor just died
            self._fail_actor_task(spec, f"cannot reach actor: {e}")

    def _flush_actor_queue(self, actor_hex: str, address: str):
        with self._actor_cv:
            queue = self._actor_queues.get(actor_hex, [])
            self._actor_queues[actor_hex] = []
        for spec in queue:
            self._send_actor_task(actor_hex, address, spec)

    def _fail_actor_queue(self, actor_hex: str, reason: str):
        with self._actor_cv:
            queue = self._actor_queues.pop(actor_hex, [])
        for spec in queue:
            self._fail_actor_task(spec, reason)

    def _fail_actor_task(self, spec: TaskSpec, reason: str):
        err = ActorDiedError(spec.actor_id, reason)
        if getattr(spec, "is_streaming", False):
            # Streams have no pre-registered returns: fail the
            # end-of-stream object so iteration raises.
            from ray_tpu.core.streaming import stream_eos_id

            self._store_value(stream_eos_id(spec.task_id), err,
                              is_error=True)
            return
        for oid in spec.return_ids:
            self._store_value(oid, err, is_error=True)

    def kill_actor(self, actor_hex: str, no_restart: bool = True):
        self.client.send({"op": "kill_actor", "actor": actor_hex,
                          "no_restart": no_restart})

    def get_named_actor(self, name: str, namespace: str = "") -> Optional[dict]:
        return self.client.call({"op": "get_named_actor", "name": name,
                                 "namespace": namespace})

    # ------------------------------------------------------------------
    def close(self):
        self._closed = True
        for conn in self._actor_conns.values():
            conn.close()
        for conn in self._node_conns.values():
            conn.close()
        self.client.close()


def func_content_id(blob: bytes) -> str:
    return hashlib.sha1(blob).hexdigest()
