"""Per-process core runtime: the counterpart of the reference's CoreWorker.

Every participating process (driver or worker) holds a CoreClient that talks
to the control server (gcs.py): object subscription/resolution, task and
actor submission, reference counting, and the shared-memory store attachment.
Reference call-stack parity: CoreWorker::SubmitTask / Put / Get
(src/ray/core_worker/core_worker.cc:2166/:1241/:1552) and the direct actor
transport (transport/direct_actor_task_submitter.cc — per-handle ordered
submission over a dedicated connection).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeoutError
from typing import Any, Dict, List, Optional, Sequence

import cloudpickle

from ray_tpu.core import object_plane, rpc, serialization
from ray_tpu.core.config import Config, get_config
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskError,
)
from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.core.task_spec import ActorCreationSpec, TaskArg, TaskSpec

_global_runtime = None
_runtime_lock = threading.Lock()

# Cached lazy import: util.tracing pulls in util/__init__ → placement
# groups → this module, so a top-level import here would cycle.
_tracing = None


def _get_tracing():
    global _tracing
    if _tracing is None:
        from ray_tpu.util import tracing

        _tracing = tracing
    return _tracing


def _make_trace_ctx():
    """Current (trace_id, parent span_id) to ride the outgoing TaskSpec,
    or None when nothing is being traced (nothing on the wire)."""
    try:
        return _get_tracing().make_trace_ctx()
    except Exception:
        return None


def _is_missing_segment_error(e: Exception) -> bool:
    """True for attach failures meaning "no longer at that location"
    (deleted arena slot / unlinked file) as opposed to real IO faults."""
    if isinstance(e, FileNotFoundError):
        return True
    try:
        from ray_tpu.native.store import ArenaError

        return isinstance(e, ArenaError)
    except ImportError:
        return False


def dump_all_stacks() -> str:
    """Format every thread's current Python stack (the in-process
    counterpart of the reference's py-spy `ray stack` dumps — no
    external profiler binary needed for cooperative processes)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        out.append(f"--- Thread {tid} ({names.get(tid, '?')}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


def get_runtime():
    if _global_runtime is None:
        raise RuntimeError(
            "ray_tpu not initialized; call ray_tpu.init() first")
    return _global_runtime


def set_runtime(rt):
    global _global_runtime
    with _runtime_lock:
        _global_runtime = rt


class _LeasePool:
    """Owner-side lease state for one task shape (resources +
    runtime_env): the granted workers, their in-flight specs, and the
    not-yet-assigned queue.  Counterpart of the per-SchedulingKey entry
    in the reference's CoreWorkerDirectTaskSubmitter
    (direct_task_transport.h:75)."""

    __slots__ = ("resources", "runtime_env", "workers", "inflight",
                 "queue", "requested", "requested_at", "idle_since",
                 "backoff_until")

    def __init__(self, resources: Dict[str, float],
                 runtime_env: Optional[dict]):
        self.resources = dict(resources)
        self.runtime_env = runtime_env
        import collections

        self.workers: Dict[str, str] = {}  # worker_hex -> address
        self.inflight: Dict[str, Dict[str, TaskSpec]] = {}
        # deque: a big burst drains via popleft; list.pop(0) would be
        # O(n^2) under the lease lock.
        self.queue = collections.deque()
        self.requested = 0  # workers asked for but not yet granted
        # When the outstanding ask was last refreshed (request sent or
        # grant received).  Pending demand the head queued indefinitely
        # (cluster saturated) must not clamp pipeline depth forever.
        self.requested_at = 0.0
        self.idle_since: Optional[float] = None
        # Set on denial (cluster saturated): no re-request until then —
        # pipeline onto what we have and retry for freed capacity.
        self.backoff_until = 0.0

    def busy(self) -> bool:
        return bool(self.queue) or any(self.inflight.values())


class CoreClient:
    """Client-side core: object futures, submission, refcounting."""

    def __init__(self, control_addr: str, worker_hex: str, kind: str,
                 address: str = "", env_key: str = "",
                 config: Optional[Config] = None, thin: bool = False):
        self.worker_hex = worker_hex
        self.kind = kind
        self.config = config or get_config()
        # Set BEFORE any rpc.Client exists: its reader thread can fire
        # _on_control_disconnect mid-__init__ (head dying in the
        # registration window), which dereferences these.
        self._closed = False
        self._reconnecting = threading.Lock()
        # Thin mode (reference Ray Client, util/client/): no shared-memory
        # attachment — every payload rides the TCP connection, so the
        # client can live on any machine that reaches the control address.
        self.thin = thin
        # Hooks must exist before the rpc recv thread can deliver pushes.
        self.on_execute_task = None
        self.on_create_actor = None
        self.on_exit = None
        # Fired after a successful control-plane reconnect (head restart
        # tolerance): workers re-announce themselves here.
        self.on_reconnect = None
        self.control_addr = control_addr
        # Must exist before the client's first call() fires _pre_call.
        self._pending_count = 0
        self._register_msg = {
            "op": "register",
            "worker_hex": worker_hex,
            "pid": os.getpid(),
            "kind": kind,
            "address": address,
            "env_key": env_key,
            "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
        }
        self.client = rpc.Client(control_addr, on_push=self._on_push,
                                 on_disconnect=self._on_control_disconnect)
        self.client._pre_call = self._flush_if_pending
        reply = self.client.call(self._register_msg)
        self.session_id = reply["session_id"]
        self.session_dir = reply["session_dir"]
        # The arena this process attaches is its NODE's (multi-host:
        # each node manager owns one; head + logical nodes share the
        # head's — gcs.py _op_register decides).
        self.store_node = reply.get("store_node", "head")
        self.store = None if thin else ShmObjectStore(
            reply.get("store_key") or self.session_id, reply["shm_dir"])
        # Single-flight table for remote-object pulls: N concurrent
        # consumers of one object in this process share ONE wire pull
        # (reference pull_manager.h request coalescing).
        self._pull_manager = object_plane.PullManager()

        # RLock: on_ref_deleted (GC __del__) takes it and can fire while
        # this same thread already holds it in a get()/put() section.
        self._lock = threading.RLock()
        # Thread-local put buffering: a worker executing a task batches
        # its result put_object messages into the task_done message (one
        # control round instead of N+1) — see worker.py _execute.
        self._tls = threading.local()
        self._object_futures: Dict[str, Future] = {}
        self._subscribed: set[str] = set()
        # Worker resource-sampler config, shared with the sampler thread
        # (worker.py _profile_sampler_loop) and retunable at runtime by
        # a head "profile_config" push (set_profile_config op).  The
        # event wakes the sampler out of its interval sleep so a toggle
        # takes effect immediately (bench A/B windows).
        self.profile_config: Dict[str, Any] = {}
        self.profile_config_ev = threading.Event()
        # Hexes whose future has resolved — maintained by done-callbacks
        # so wait() is a set-membership check + condition wait instead
        # of an O(n) future-lock scan per call.
        self._resolved: set = set()
        self._resolved_cond = threading.Condition()
        # Owner-direct actor results (the control plane is OFF the actor
        # hot path — reference direct_actor_task_submitter.cc): futures
        # resolved by pushes on the direct actor connection, never
        # registered with the head unless the ref escapes this process.
        self._direct_futures: Dict[str, Future] = {}
        self._direct_inflight: Dict[str, set] = {}  # actor_hex -> obj hexes
        # Delivered direct specs kept for resubmission across an actor
        # RESTART (only when the actor was created with
        # max_task_retries > 0); obj_hex -> TaskSpec.
        self._direct_inflight_specs: Dict[str, TaskSpec] = {}
        self._direct_actor_of: Dict[str, str] = {}  # obj hex -> actor_hex
        # Direct refs that escaped (were serialized into another task /
        # put) before or after resolving: the head got a registration and
        # must receive the value once it lands (ownership promotion).
        self._direct_promoted: set[str] = set()
        # Submit-side coalescing: actor-task sends queue per address and
        # flush as ONE actor_task_batch frame at the next get()/wait()
        # (or a 2 ms timer / 64-spec cap for fire-and-forget callers).
        # On a contended host this amortizes the per-call syscall +
        # wakeup cost across the burst — the reference gets the same
        # effect from gRPC stream batching.
        # RLocks, deliberately: ObjectRef.__del__ fires from GC at
        # ARBITRARY points — including while this same thread is inside
        # a section holding these locks (observed: a Thread.__init__
        # allocation inside _queue_for_flush triggered GC -> __del__ ->
        # on_ref_deleted -> flush -> self-deadlock on a plain Lock).
        # The __del__ path only appends to the queues, which is safe to
        # re-enter.
        self._send_lock = threading.RLock()
        # Serializes whole flushes (swap + send): two flushers racing
        # (inline at get() vs the 2 ms background thread) must not
        # reorder an incref frame ahead of the submit that registers
        # its object.
        self._flush_mutex = threading.RLock()
        self._pending_direct: Dict[str, List[TaskSpec]] = {}
        self._pending_pool: Dict[str, List[TaskSpec]] = {}
        self._pending_submits: List[TaskSpec] = []
        # Owner-direct task leases (reference: the lease protocol of
        # CoreWorkerDirectTaskSubmitter, direct_task_transport.h:75 —
        # RequestNewWorkerIfNeeded :353 leases workers from the
        # scheduler; the owner then pushes specs peer-to-peer and
        # reuses the lease while same-shaped work remains, OnWorkerIdle
        # :197).  One pool per task shape.
        self._lease_lock = threading.RLock()
        self._leases: Dict[tuple, "_LeasePool"] = {}
        # Shapes with backlogged submissions awaiting a flusher-thread
        # pump (split submit path, _submit_via_lease).
        self._pump_shapes: set = set()
        self._lease_tokens: Dict[int, tuple] = {}  # token -> shape key
        self._lease_token_seq = 0
        self._lease_of_obj: Dict[str, tuple] = {}  # obj -> (shape, whex, task_hex)
        self._lease_addr_workers: Dict[str, set] = {}  # addr -> worker hexes
        self._lease_request_pending = False
        # Objects this process itself stored (put / stored returns):
        # their refs are resolvable without waiting, so tasks using them
        # as args stay lease-eligible.
        self._local_known: set = set()
        # Small put payloads kept for arg hydration: a resolved ref arg
        # whose bytes we hold ships INLINE in the spec instead of making
        # the executor fetch it (reference: the DependencyResolver
        # inlines small resolved deps, transport/dependency_resolver.cc).
        self._inline_cache: Dict[str, bytes] = {}
        self._inline_cache_bytes = 0
        self._flush_ev = threading.Event()
        self._flusher_started = False
        # actor state tracking
        self._actor_state: Dict[str, dict] = {}
        self._actor_cv = threading.Condition()
        self._actor_conns: Dict[str, rpc.Client] = {}
        # Connections to other nodes' object servers (cross-node pulls).
        self._node_conns: Dict[str, rpc.Client] = {}
        self._actor_queues: Dict[str, List[TaskSpec]] = {}
        self._sent_funcs: set[str] = set()

    # ------------------------------------------------------------------
    # Control-plane reconnection (reference: raylet/worker redial after
    # GCS restart, NotifyGCSRestart node_manager.proto:383).
    def _on_control_disconnect(self):
        if self._closed:
            return
        if self.config.gcs_reconnect_timeout_s <= 0:
            if self.on_exit is not None:
                self.on_exit()
            return
        # One loop at a time: a flapping head must not stack concurrent
        # reconnectors racing writes to self.client.
        if not self._reconnecting.acquire(blocking=False):
            return
        threading.Thread(target=self._reconnect_loop,
                         name="control-reconnect", daemon=True).start()

    def _reconnect_loop(self):
        try:
            self._reconnect_loop_inner()
        finally:
            self._reconnecting.release()
        # A drop during the adoption/resync window fires the callback
        # while _reconnecting is still held (swallowed by the
        # non-blocking acquire) — recheck now that it's released.
        client = self.client
        if not self._closed and getattr(client, "_closed", False):
            self._on_control_disconnect()

    def _reconnect_loop_inner(self):
        deadline = time.monotonic() + self.config.gcs_reconnect_timeout_s
        delay = 0.2
        while not self._closed and time.monotonic() < deadline:
            client = None
            try:
                # No on_disconnect on the probe: a flap during resync
                # must not spawn a second loop; the callback is attached
                # only once this client is adopted.
                client = rpc.Client(
                    self.control_addr, on_push=self._on_push,
                    connect_timeout=1.0)
                client.call(self._register_msg, timeout=10.0)
                # Re-subscribe everything unresolved.  grace=True: the
                # restarted head fails objects nobody re-produces within
                # its grace window instead of leaving gets hanging.
                with self._lock:
                    pending = [
                        h for h in self._subscribed
                        if (f := self._object_futures.get(h)) is not None
                        and not f.done()]
                with self._actor_cv:
                    actors = set(self._actor_state) | \
                        set(self._actor_queues)
                if pending:
                    client.send({"op": "subscribe_objects",
                                 "objs": pending, "grace": True})
                for actor_hex in actors:
                    client.send({"op": "subscribe_actor",
                                 "actor": actor_hex})
            except Exception:
                if client is not None:
                    client.close()
                time.sleep(delay)
                delay = min(delay * 1.7, 2.0)
                continue
            client._on_disconnect = self._on_control_disconnect
            client._pre_call = self._flush_if_pending
            if client._closed:
                # Dropped between resync and adoption: the callback we
                # just attached never fires for that earlier loss.
                client.close()
                time.sleep(delay)
                continue
            self.client = client
            # The restarted head rebuilt worker states from re-announces
            # and knows nothing of our leases: drop granted workers
            # (in-flight results still arrive on their live direct
            # conns) and let the pump re-request against the new head.
            with self._lease_lock:
                self._lease_tokens.clear()
                # _lease_addr_workers is deliberately KEPT: in-flight
                # specs survive the restart, and a later death of their
                # worker must still map the dropped connection back to
                # the worker hex to fail them over.
                for shape, pool in self._leases.items():
                    pool.workers.clear()
                    pool.requested = 0
                    if pool.queue:
                        self._pump_lease_locked(shape, pool)
            # Anything stranded by a mid-outage flush failure goes out
            # now that a live connection exists.
            if self._pending_count:
                self._flush_ev.set()
            cb = self.on_reconnect
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass
            return
        # Could not reach a head within the window: give up the same way
        # a worker death would.
        if self.on_exit is not None:
            self.on_exit()

    def _on_push(self, msg: dict):
        op = msg.get("op")
        if op == "object_ready":
            with self._lock:
                fut = self._object_futures.get(msg["obj"])
            if fut is not None and not fut.done():
                fut.set_result(msg)
        elif op == "actor_update":
            self._handle_actor_update(msg)
        elif op == "execute_task" and self.on_execute_task is not None:
            self.on_execute_task(msg["spec"])
        elif op == "create_actor_instance" and self.on_create_actor is not None:
            self.on_create_actor(msg["spec"])
        elif op == "lease_granted":
            self._on_lease_granted(msg)
        elif op == "lease_revoked":
            self._on_lease_worker_lost(msg["worker"],
                                       msg.get("reason", "worker died"))
        elif op == "profile":
            # On-demand profiling (gcs.py _op_profile_worker): run off
            # the push thread; the worker keeps executing its task.
            threading.Thread(target=self._run_profile, args=(msg,),
                             name="profile", daemon=True).start()
        elif op == "collect_spans":
            # Cluster span harvest (gcs._op_harvest_spans): serve off
            # the push thread — serializing a 2048-span chunk inline
            # would stall task dispatch/result traffic behind it on a
            # busy process.  The reply is one-way; the head matches it
            # to its waiter by token (profile_result pattern), and it
            # never issues the next chunk request until this reply
            # lands, so off-thread serving can't reorder chunks.
            threading.Thread(target=self._serve_collect_spans,
                             args=(msg,), name="collect-spans",
                             daemon=True).start()
        elif op == "profile_config":
            # Head retuning every worker's resource sampler at runtime
            # (set_profile_config): just update shared state — the
            # sampler thread (worker.py) re-reads it each wakeup.
            cfg = self.profile_config
            if msg.get("enabled") is not None:
                cfg["enabled"] = bool(msg["enabled"])
            if msg.get("interval_s") is not None:
                try:
                    cfg["interval_s"] = max(0.05, float(msg["interval_s"]))
                except (TypeError, ValueError):
                    pass
            self.profile_config_ev.set()
        elif op == "exit" and self.on_exit is not None:
            self.on_exit()

    def _serve_collect_spans(self, msg: dict):
        try:
            out = _get_tracing().collect_spans_since(
                int(msg.get("cursor", 0) or 0),
                max_spans=int(msg.get("limit", 2048) or 2048))
        except Exception:
            out = {"rows": [], "cursor": 0, "missed": 0}
        try:
            self.client.send({
                "op": "collect_spans_result", "token": msg.get("token"),
                "cursor": out["cursor"], "rows": out["rows"],
                "missed": out["missed"], "pid": os.getpid(),
                "worker": self.worker_hex})
        except Exception:
            pass

    def _run_profile(self, msg: dict):
        kind = msg.get("kind", "stack")
        try:
            if kind == "stack":
                data = dump_all_stacks()
            elif kind == "jax_trace":
                import time as _time

                import jax

                out_dir = os.path.join(
                    self.session_dir, "profiles",
                    f"{self.worker_hex[:8]}-{int(_time.time())}")
                os.makedirs(out_dir, exist_ok=True)
                # Process-wide xplane trace: captures any jitted work the
                # task threads run during the window (viewable with
                # tensorboard / xprof).
                with jax.profiler.trace(out_dir):
                    _time.sleep(float(msg.get("duration_s", 2.0)))
                data = out_dir
            else:
                data = f"unknown profile kind {kind!r}"
        except Exception as e:  # noqa: BLE001
            data = f"profile failed: {type(e).__name__}: {e}"
        if "_local_result" in msg:  # self-profile (state/api.py)
            msg["_local_result"]["data"] = data
            return
        try:
            self.client.send({"op": "profile_result",
                              "token": msg.get("token"), "data": data})
        except Exception:
            pass

    def _handle_actor_update(self, msg: dict):
        actor_hex = msg["actor"]
        with self._actor_cv:
            self._actor_state[actor_hex] = msg
            self._actor_cv.notify_all()
        if msg["state"] == "ALIVE":
            self._flush_actor_queue(actor_hex, msg["address"])
        elif msg["state"] == "DEAD":
            self._fail_actor_queue(actor_hex, msg.get("reason", ""))
            self._fail_direct_inflight(actor_hex, msg.get("reason", ""))
        elif msg["state"] == "RESTARTING":
            # Tasks already DELIVERED to the dead instance are lost (the
            # restarted instance never sees them); queued ones re-flush
            # on ALIVE.  With max_task_retries they resubmit to the
            # restarted instance; otherwise this mirrors the head's
            # _fail_actor_inflight for the registered (non-direct) path.
            self._fail_direct_inflight(
                actor_hex, msg.get("reason", "actor restarting"),
                retryable=True)

    # ------------------------------------------------------------------
    # Owner-direct actor results: the result of a plain (1-return,
    # non-streaming) actor call is pushed straight back on the direct
    # actor connection; the head is not involved unless the ref escapes
    # this process (promotion) or the result is too large for the wire.
    def _mark_resolved(self, obj_hex: str):
        with self._resolved_cond:
            self._resolved.add(obj_hex)
            self._resolved_cond.notify_all()

    def _track_resolution(self, obj_hex: str, fut: Future):
        fut.add_done_callback(lambda f, h=obj_hex: self._mark_resolved(h))

    def _register_direct(self, obj_hex: str, actor_hex: str) -> Future:
        fut = Future()
        with self._lock:
            self._direct_futures[obj_hex] = fut
            self._direct_actor_of[obj_hex] = actor_hex
        self._track_resolution(obj_hex, fut)
        return fut

    def _mark_direct_delivered(self, spec):
        """The spec was actually sent to a live instance: its results are
        now at risk of that instance's death.  Actors created with
        max_task_retries keep the spec around so a RESTART resubmits it
        instead of failing the caller."""
        if not getattr(spec, "direct", False):
            return
        actor_hex = spec.actor_id.hex()
        with self._actor_cv:
            st = self._actor_state.get(actor_hex) or {}
            retryable = st.get("max_task_retries", 0) > 0
        with self._lock:
            for oid in spec.return_ids:
                if oid.hex() in self._direct_futures:
                    self._direct_inflight.setdefault(
                        actor_hex, set()).add(oid.hex())
                    if retryable:
                        self._direct_inflight_specs[oid.hex()] = spec

    def _on_direct_push(self, msg: dict):
        op = msg.get("op")
        if op == "direct_result":
            self._resolve_direct(
                msg["obj"], {"direct": True, "data": msg["data"],
                             "is_error": msg.get("is_error", False)})
        elif op == "direct_result_batch":
            results = msg["results"]
            promoted = []
            with self._lock:
                resolved = []
                for obj_hex, data, is_error in results:
                    fut = self._direct_futures.get(obj_hex)
                    actor_hex = self._direct_actor_of.get(obj_hex, "")
                    self._direct_inflight.get(
                        actor_hex, set()).discard(obj_hex)
                    if obj_hex in self._direct_promoted:
                        promoted.append((obj_hex, data, is_error))
                    resolved.append((fut, data, is_error))
            for obj_hex, data, is_error in promoted:
                try:
                    self.client.send({
                        "op": "put_object", "obj": obj_hex,
                        "size": len(data), "inline": bytes(data),
                        "is_error": is_error})
                except Exception:
                    pass
            for obj_hex, _, _ in results:
                self._lease_task_completed(obj_hex)
            for fut, data, is_error in resolved:
                if fut is not None and not fut.done():
                    fut.set_result({"direct": True, "data": data,
                                    "is_error": is_error})
        elif op == "direct_result_remote":
            # Result was too large for the wire: the worker stored it via
            # the head (shm path); chain the head subscription into the
            # local direct future.
            obj_hex = msg["obj"]
            # The worker is done with the task either way: free its
            # lease pipeline slot now, not when the owner resolves.
            self._lease_task_completed(obj_hex)
            with self._lock:
                # The head now holds an entry (refcount 1 from the
                # worker's put): mark it head-known so this ref's
                # deletion sends the decref — otherwise every oversized
                # direct result would pin head memory forever.
                self._direct_promoted.add(obj_hex)
                fut = self._direct_futures.get(obj_hex)
                head_fut = self._object_futures.get(obj_hex)
                if head_fut is None:
                    head_fut = Future()
                    self._object_futures[obj_hex] = head_fut
                    self._track_resolution(obj_hex, head_fut)
                if obj_hex not in self._subscribed:
                    self._subscribed.add(obj_hex)
                    self.client.send({"op": "subscribe_objects",
                                      "objs": [obj_hex]})
            if fut is None:
                return

            def _chain(hf, fut=fut, obj_hex=obj_hex):
                self._lease_task_completed(obj_hex)
                with self._lock:
                    self._direct_inflight.get(
                        self._direct_actor_of.get(obj_hex, ""),
                        set()).discard(obj_hex)
                if fut.done():
                    return
                try:
                    fut.set_result(hf.result(timeout=0))
                except BaseException as e:  # noqa: BLE001
                    fut.set_exception(e)

            head_fut.add_done_callback(_chain)

    def _resolve_direct(self, obj_hex: str, info: dict):
        self._lease_task_completed(obj_hex)
        with self._lock:
            fut = self._direct_futures.get(obj_hex)
            actor_hex = self._direct_actor_of.get(obj_hex, "")
            self._direct_inflight.get(actor_hex, set()).discard(obj_hex)
            self._direct_inflight_specs.pop(obj_hex, None)
            promoted = obj_hex in self._direct_promoted
        if promoted:
            # The ref escaped before the value landed: forward the bytes
            # to the head so remote holders resolve.
            try:
                self.client.send({
                    "op": "put_object", "obj": obj_hex,
                    "size": len(info["data"]), "inline": bytes(info["data"]),
                    "is_error": info.get("is_error", False)})
            except Exception:
                pass
        if fut is not None and not fut.done():
            fut.set_result(info)

    def _fail_direct(self, obj_hex: str, err: Exception):
        from ray_tpu.core import serialization

        self._lease_task_completed(obj_hex)
        with self._lock:
            fut = self._direct_futures.get(obj_hex)
            actor_hex = self._direct_actor_of.get(obj_hex, "")
            self._direct_inflight.get(actor_hex, set()).discard(obj_hex)
            promoted = obj_hex in self._direct_promoted
        if fut is not None and fut.done():
            # Already resolved (result raced the failure notification):
            # a stale inflight entry must NOT overwrite the delivered —
            # possibly promoted — value with an actor-died error.
            return
        data = serialization.serialize(err).to_bytes()
        if promoted:
            try:
                self.client.send({
                    "op": "put_object", "obj": obj_hex, "size": len(data),
                    "inline": data, "is_error": True})
            except Exception:
                pass
        if fut is not None and not fut.done():
            fut.set_result({"direct": True, "data": data,
                            "is_error": True})

    def _fail_direct_inflight(self, actor_hex: str, reason: str,
                              retryable: bool = False):
        """Tasks delivered to a dead actor instance.  retryable=True
        (the actor is RESTARTING): specs with max_task_retries budget
        left re-queue for the restarted instance — the owner is the
        only party holding the spec on the direct path, so the retry
        happens here, not at the head (reference
        direct_actor_task_submitter retry-on-restart).  Everything else
        fails with ActorDiedError."""
        with self._lock:
            pending = list(self._direct_inflight.pop(actor_hex, ()))
            specs = {h: self._direct_inflight_specs.pop(h, None)
                     for h in pending}
        if not pending:
            return
        with self._actor_cv:
            mtr = (self._actor_state.get(actor_hex)
                   or {}).get("max_task_retries", 0)
        err = ActorDiedError(actor_hex, reason or "actor died")
        retried = []
        for obj_hex in pending:
            spec = specs.get(obj_hex)
            if retryable and spec is not None and spec.retry_count < mtr:
                spec.retry_count += 1
                retried.append(spec)
            else:
                self._fail_direct(obj_hex, err)
        for spec in retried:
            # Actor state is RESTARTING: this queues the spec and it
            # flushes when the ALIVE update lands.
            self._route_actor_task(actor_hex, spec)

    def _maybe_promote_direct(self, obj_hex: str):
        """The ref is escaping this process (serialized into a task arg /
        put): make it resolvable via the head.  Resolved → forward the
        bytes now; pending → register (tied to its actor so actor death
        fails remote waiters too) and forward on arrival."""
        with self._lock:
            fut = self._direct_futures.get(obj_hex)
            if fut is None or obj_hex in self._direct_promoted:
                return
            self._direct_promoted.add(obj_hex)
            actor_hex = self._direct_actor_of.get(obj_hex, "")
        self.client.send({"op": "register_objects", "objs": [obj_hex],
                          "actor": actor_hex})
        if fut.done():
            info = fut.result(timeout=0)
            if info.get("direct"):
                try:
                    self.client.send({
                        "op": "put_object", "obj": obj_hex,
                        "size": len(info["data"]),
                        "inline": bytes(info["data"]),
                        "is_error": info.get("is_error", False)})
                except Exception:
                    pass
        # pending: _resolve_direct / _fail_direct forwards on arrival

    # ------------------------------------------------------------------
    # Owner-direct task leases.  The reference's normal-task hot path
    # (CoreWorkerDirectTaskSubmitter, direct_task_transport.h:75): the
    # owner leases workers from the scheduler once per task shape
    # (RequestNewWorkerIfNeeded :353), pushes specs peer-to-peer
    # (PushNormalTask :601), reuses idle leases (OnWorkerIdle :197) and
    # returns them when the shape's queue drains.  Results ride the
    # same direct connection back; the head is only involved in the
    # lease grant/return and never sees individual tasks.
    def _lease_eligible(self, spec: TaskSpec) -> bool:
        # Thin clients lease too: the direct worker connections are
        # plain TCP (cross-host safe); only shm attachment is off.
        if not self.config.direct_task_leases:
            return False
        if spec.is_streaming or spec.num_returns != 1:
            return False
        if spec.placement_group_hex or spec.scheduling_strategy is not None:
            return False
        # Every arg must be resolvable without waiting: a leased worker
        # blocking on an unproduced upstream object would hold the
        # lease's resources and can deadlock the pool; the head path
        # queues dep-pending tasks instead (reference: the owner-side
        # DependencyResolver waits before pushing,
        # transport/dependency_resolver.cc).
        for a in spec.args:
            if a.is_ref and not self._ref_resolved(a.object_hex):
                return False
        return True

    def _ref_resolved(self, obj_hex: str) -> bool:
        with self._lock:
            if obj_hex in self._local_known:
                return True
            fut = self._direct_futures.get(obj_hex)
            if fut is None:
                fut = self._object_futures.get(obj_hex)
            return fut is not None and fut.done()

    @staticmethod
    def _shape_of(spec: TaskSpec) -> tuple:
        env_part = ""
        if spec.runtime_env:
            import json

            env_part = hashlib.sha1(json.dumps(
                spec.runtime_env, sort_keys=True).encode()).hexdigest()[:8]
        return (tuple(sorted(spec.resources.items())), env_part)

    def _submit_via_lease(self, spec: TaskSpec):
        spec.direct = True
        self._register_direct(spec.return_ids[0].hex(), "")
        shape = self._shape_of(spec)
        defer = False
        with self._lease_lock:
            pool = self._leases.get(shape)
            if pool is None:
                pool = self._leases[shape] = _LeasePool(
                    spec.resources, spec.runtime_env)
            was_backlogged = bool(pool.queue)
            pool.queue.append(spec)
            pool.idle_since = None
            if was_backlogged:
                # Burst in progress: the workers are saturated (an
                # earlier pump left a backlog), so pumping again per
                # submit only re-sorts the same full pipelines.  Append
                # and let the flusher thread + completion backfills
                # drive assignment — submission overlaps with dispatch
                # and completion draining instead of serializing with
                # them (r4's single_client_tasks_async gap).
                self._pump_shapes.add(shape)
                defer = True
            else:
                self._pump_lease_locked(shape, pool)
        if defer:
            self._ensure_flusher()
            self._flush_ev.set()

    def _pump_deferred_pools(self):
        """Flusher-thread half of the split submit path: assign any
        backlogged shapes' specs to workers (then the same flush cycle
        carries the sends)."""
        with self._lease_lock:
            shapes = list(self._pump_shapes)
            self._pump_shapes.clear()
            for shape in shapes:
                pool = self._leases.get(shape)
                if pool is not None:
                    self._pump_lease_locked(shape, pool)

    def _pump_lease_locked(self, shape: tuple, pool: "_LeasePool"):
        """Lease lock held.  Assign queued specs to granted workers with
        pipeline headroom; ask the head for workers for the rest."""
        depth = self.config.lease_pipeline_depth
        # While more workers are expected IMMINENTLY (granted or
        # spawning), hold pipelining at 1 so concurrent tasks land on
        # distinct workers (parity with the reference's
        # one-lease-per-running-task default); once the fleet is
        # settled — grants exhausted, denied, or the ask has sat
        # unanswered past the scale-up window (the head queued it for a
        # saturated cluster) — pipeline to full depth to absorb the
        # backlog on the workers we do hold.
        if pool.requested > 0 and \
                time.monotonic() - pool.requested_at \
                < self.config.lease_scaleup_clamp_s and \
                len(pool.workers) < self.config.max_lease_workers_per_request:
            depth = 1
        assigns = []
        if pool.queue and pool.workers:
            # Breadth-first, least-loaded first: concurrent tasks land
            # on distinct (ideally empty) workers; pipelining only
            # absorbs backlog beyond the fleet cap.
            order = sorted(pool.workers.items(),
                           key=lambda kv: len(pool.inflight.get(kv[0], ())))
            progress = True
            while pool.queue and progress:
                progress = False
                for whex, addr in order:
                    if not pool.queue:
                        break
                    infl = pool.inflight.setdefault(whex, {})
                    if len(infl) >= depth:
                        continue
                    spec = pool.queue.popleft()
                    task_hex = spec.task_id.hex()
                    infl[task_hex] = spec
                    self._lease_of_obj[spec.return_ids[0].hex()] = (
                        shape, whex, task_hex)
                    assigns.append((whex, addr, spec))
                    progress = True
        for whex, addr, spec in assigns:
            key = "lease:" + whex
            obj_hex = spec.return_ids[0].hex()
            with self._lock:
                self._direct_actor_of[obj_hex] = key
                self._direct_inflight.setdefault(key, set()).add(obj_hex)
            self._queue_for_flush("pool", addr, spec)
        if pool.queue and time.monotonic() >= pool.backoff_until and \
                min(len(pool.workers) + len(pool.queue),
                    self.config.max_lease_workers_per_request) \
                - len(pool.workers) - pool.requested > 0:
            # Worker deficit: DEFER the request to the flusher so a
            # submit burst coalesces into one request_lease carrying
            # the whole count — N count=1 requests would each pick a
            # spawn node with no view of the others' demand and stack
            # every spawn on the same node.
            self._lease_request_pending = True
            self._ensure_flusher()
            self._flush_ev.set()

    def _send_lease_requests(self):
        """Deferred lease requests (one per shape, batched count)."""
        if not getattr(self, "_lease_request_pending", False):
            return
        self._lease_request_pending = False
        with self._lease_lock:
            now = time.monotonic()
            for shape, pool in self._leases.items():
                if not pool.queue or now < pool.backoff_until:
                    continue
                # Desired fleet: one worker per still-queued task
                # (tasks that could run concurrently must not serialize
                # behind a pipeline), capped.
                desired = min(len(pool.workers) + len(pool.queue),
                              self.config.max_lease_workers_per_request)
                ask = desired - len(pool.workers) - pool.requested
                if ask <= 0:
                    continue
                self._lease_token_seq += 1
                token = self._lease_token_seq
                self._lease_tokens[token] = [shape, ask]
                pool.requested += ask
                pool.requested_at = time.monotonic()
                try:
                    self.client.send({
                        "op": "request_lease", "token": token,
                        "resources": pool.resources,
                        "runtime_env": pool.runtime_env, "count": ask,
                        # Workers we already hold: with none, the head
                        # must queue (not deny) an unsatisfiable request
                        # so the demand stays visible to the autoscaler.
                        "have": len(pool.workers)})
                except Exception:
                    pool.requested -= ask
                    self._lease_tokens.pop(token, None)

    def _on_lease_granted(self, msg: dict):
        workers = msg.get("workers", ())
        denied = int(msg.get("denied", 0))
        error = msg.get("error", "")
        token = msg.get("token")
        give_back = []
        failed_specs: List[TaskSpec] = []
        with self._lease_lock:
            ent = self._lease_tokens.get(token)
            if ent is None:
                # Lease pool already released (queue drained while the
                # request was in flight): hand the workers straight back.
                give_back = [w["worker"] for w in workers]
                pool = shape = None
            else:
                shape = ent[0]
                ent[1] -= len(workers) + denied
                if ent[1] <= 0:
                    self._lease_tokens.pop(token, None)
                pool = self._leases.get(shape)
                if pool is None:
                    give_back = [w["worker"] for w in workers]
                else:
                    pool.requested = max(
                        0, pool.requested - len(workers) - denied)
                    if workers and pool.requested:
                        # Grants are flowing: keep the scale-up clamp
                        # alive for the remainder of the ask.
                        pool.requested_at = time.monotonic()
                    if denied:
                        # Saturated (or broken env): back off before
                        # re-requesting; keep pipelining what we have.
                        # Applies to partial grants too — immediately
                        # re-asking for the denied remainder would churn
                        # one request/denial per flusher cycle.
                        pool.backoff_until = time.monotonic() + 0.25
                    if error:
                        # Permanent denial (runtime_env setup failed):
                        # fail the queued specs like the head path's
                        # unschedulable fast-fail.
                        import collections

                        failed_specs = list(pool.queue)
                        pool.queue = collections.deque()
                    for w in workers:
                        whex, addr = w["worker"], w["address"]
                        pool.workers[whex] = addr
                        self._lease_addr_workers.setdefault(
                            addr, set()).add(whex)
                    self._pump_lease_locked(shape, pool)
        if failed_specs:
            from ray_tpu.core.exceptions import RuntimeEnvSetupError

            for spec in failed_specs:
                # No worker will ever _finish these specs (same contract
                # as the cancel paths below): release their borrowed args
                # or they stay pinned at the head for the session.
                for bhex in spec.borrows:
                    self._queue_for_flush("decref", None, bhex)
                self._fail_direct(spec.return_ids[0].hex(),
                                  RuntimeEnvSetupError(error))
        if give_back:
            try:
                self.client.send({"op": "release_lease",
                                  "workers": give_back})
            except Exception:
                pass
        # Ship the assignments now — the granting push arrived on the
        # rpc reader thread; the submitting thread may be parked in
        # get() already.
        self._flush_if_pending()

    def _lease_task_completed(self, obj_hex: str):
        """A direct task's result (or failure) arrived: free its
        pipeline slot and feed the lease more work / start its idle
        clock (reference OnWorkerIdle, direct_task_transport.cc:197)."""
        with self._lease_lock:
            ent = self._lease_of_obj.pop(obj_hex, None)
            if ent is None:
                return
            shape, whex, task_hex = ent
            pool = self._leases.get(shape)
            if pool is None:
                return
            pool.inflight.get(whex, {}).pop(task_hex, None)
            if pool.queue:
                self._pump_lease_locked(shape, pool)
            elif not pool.busy():
                pool.idle_since = time.monotonic()

    def _on_lease_worker_lost(self, whex: str, reason: str):
        """A leased worker died (direct connection broke, or the head
        pushed lease_revoked): owner-side retry of its in-flight specs
        through the head path, mirroring the reference's owner-side
        TaskManager retries (task_manager.h:208)."""
        specs: List[TaskSpec] = []
        shape = None
        with self._lease_lock:
            for s, p in self._leases.items():
                # Match by inflight too: a reconnect drops granted
                # workers but keeps their in-flight specs, which must
                # still fail over if the worker then dies.
                if whex in p.workers or p.inflight.get(whex):
                    shape = s
                    pool = p
                    break
            else:
                return
            addr = pool.workers.pop(whex, None)
            if addr is not None:
                peers = self._lease_addr_workers.get(addr)
                if peers is not None:
                    peers.discard(whex)
                    if not peers:
                        self._lease_addr_workers.pop(addr, None)
            for task_hex, spec in pool.inflight.pop(whex, {}).items():
                self._lease_of_obj.pop(spec.return_ids[0].hex(), None)
                specs.append(spec)
        with self._lock:
            self._direct_inflight.pop("lease:" + whex, None)
        from ray_tpu.core.exceptions import WorkerCrashedError

        for spec in specs:
            if spec.retry_count < spec.max_retries:
                spec.retry_count += 1
                self._lease_fallback_resubmit(spec)
            else:
                self._fail_direct(
                    spec.return_ids[0].hex(),
                    WorkerCrashedError(
                        f"task {spec.name or spec.task_id.hex()}: "
                        f"worker died: {reason}"))
        with self._lease_lock:
            pool = self._leases.get(shape)
            if pool is not None and pool.queue:
                self._pump_lease_locked(shape, pool)

    def _lease_fallback_resubmit(self, spec: TaskSpec):
        """Re-route a direct spec through the head's scheduler (worker
        died / lease unavailable): the head registers its returns from
        the spec, and the owner's direct future chains onto the head
        subscription."""
        spec.direct = False
        obj_hex = spec.return_ids[0].hex()
        # Sent inline (not queued): the subscribe below must reach the
        # head AFTER the submit registers the return object.
        try:
            self.client.send({"op": "submit_task", "spec": spec})
        except Exception:
            return  # control plane down; reconnect path re-resolves
        self._chain_head_to_direct(obj_hex)

    def _chain_head_to_direct(self, obj_hex: str):
        """Resolve a direct future from the head's object subscription
        (the same promotion the oversized direct_result_remote path
        uses)."""
        with self._lock:
            fut = self._direct_futures.get(obj_hex)
            head_fut = self._object_futures.get(obj_hex)
            if head_fut is None:
                head_fut = Future()
                self._object_futures[obj_hex] = head_fut
                self._track_resolution(obj_hex, head_fut)
            if obj_hex not in self._subscribed:
                self._subscribed.add(obj_hex)
                self.client.send({"op": "subscribe_objects",
                                  "objs": [obj_hex]})
        if fut is None or fut is head_fut:
            return

        def _chain(hf, fut=fut):
            if fut.done():
                return
            try:
                fut.set_result(hf.result(timeout=0))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        head_fut.add_done_callback(_chain)

    def _sweep_idle_leases(self):
        """Return leases idle past the timeout (reference
        OnWorkerIdle lease return after worker_lease_timeout)."""
        now = time.monotonic()
        to_release: List[str] = []
        with self._lease_lock:
            for shape, pool in list(self._leases.items()):
                if pool.busy():
                    pool.idle_since = None
                    # Backed-off pool whose window expired: retry the
                    # lease request for freed capacity.
                    if pool.queue and pool.requested == 0 and \
                            now >= pool.backoff_until:
                        self._pump_lease_locked(shape, pool)
                    continue
                if pool.idle_since is None:
                    pool.idle_since = now
                    continue
                if now - pool.idle_since < self.config.lease_idle_timeout_s:
                    continue
                for whex, addr in pool.workers.items():
                    to_release.append(whex)
                    peers = self._lease_addr_workers.get(addr)
                    if peers is not None:
                        peers.discard(whex)
                        if not peers:
                            self._lease_addr_workers.pop(addr, None)
                del self._leases[shape]
                # Outstanding request tokens for the released pool
                # would otherwise linger forever (their late grants hit
                # the pool-is-gone give-back path without consuming the
                # token when partially filled).
                for tok in [t for t, ent in self._lease_tokens.items()
                            if ent[0] == shape]:
                    self._lease_tokens.pop(tok, None)
        if to_release:
            try:
                self.client.send({"op": "release_lease",
                                  "workers": to_release})
            except Exception:
                pass

    def _release_all_leases(self):
        with self._lease_lock:
            workers = [whex for pool in self._leases.values()
                       for whex in pool.workers]
            self._leases.clear()
            self._lease_addr_workers.clear()
            self._lease_tokens.clear()
        if workers:
            try:
                self.client.send({"op": "release_lease",
                                  "workers": workers})
            except Exception:
                pass

    def cancel_ref(self, obj_hex: str, force: bool = False) -> bool:
        """ray.cancel() entry: lease-path tasks are the owner's to
        cancel (the head never saw them); everything else goes to the
        head (reference: CancelTask is owner-initiated,
        core_worker.proto:441)."""
        from ray_tpu.core.exceptions import TaskCancelledError

        with self._lease_lock:
            # Queued, not yet assigned: drop it locally.
            for pool in self._leases.values():
                for i, spec in enumerate(pool.queue):
                    if spec.return_ids and \
                            spec.return_ids[0].hex() == obj_hex:
                        del pool.queue[i]
                        # No worker will ever _finish this spec: the
                        # borrow decrefs are the owner's to issue, or
                        # the args stay pinned for the session.
                        for bhex in spec.borrows:
                            self._queue_for_flush("decref", None, bhex)
                        self._fail_direct(obj_hex, TaskCancelledError(
                            f"task {spec.name or spec.task_id.hex()}: "
                            "task cancelled"))
                        return True
            ent = self._lease_of_obj.get(obj_hex)
        if ent is not None:
            if not force:
                # Dispatched, but possibly still QUEUED on the worker
                # (pipelined behind a running task).  Ask the executor to
                # drop it from its queue — the reference cancels here too
                # (normal_scheduling_queue CancelTaskIfFound); only a
                # task that already started is uncancellable sans force.
                shape, whex, task_hex = ent
                with self._lease_lock:
                    pool = self._leases.get(shape)
                    addr = pool.workers.get(whex) if pool else None
                if addr is None:
                    return False
                # The spec may still sit in the coalescing send buffer —
                # a direct .call() would overtake it on the socket and
                # the worker would truthfully say "not queued".  Cancel
                # it right out of the buffer when possible; flush
                # otherwise so the queue scan sees it.
                dropped = None
                with self._send_lock:  # NB: never nest _lease_lock inside
                    specs = self._pending_pool.get(addr, [])
                    for i, s in enumerate(specs):
                        if s.task_id is not None \
                                and s.task_id.hex() == task_hex:
                            del specs[i]
                            self._pending_count -= 1
                            dropped = s
                            break
                if dropped is not None:
                    for bhex in dropped.borrows:  # no worker will _finish it
                        self._queue_for_flush("decref", None, bhex)
                    self._fail_direct(obj_hex, TaskCancelledError(
                        "task cancelled"))
                    return True
                self._flush_direct_sends()
                try:
                    reply = self._actor_conn(addr).call(
                        {"op": "cancel_pool_task", "task": task_hex},
                        timeout=10.0)
                except Exception:
                    return False
                if not (reply or {}).get("cancelled"):
                    return False  # already executing
                self._fail_direct(obj_hex, TaskCancelledError(
                    "task cancelled"))
                return True
            shape, whex, task_hex = ent
            with self._lease_lock:
                pool = self._leases.get(shape)
                spec = pool.inflight.get(whex, {}).get(task_hex) \
                    if pool is not None else None
            if spec is not None:
                spec.max_retries = spec.retry_count  # no retry on kill
            self._fail_direct(obj_hex, TaskCancelledError(
                "task cancelled (force)"))
            try:
                self.client.send({"op": "kill_worker", "worker": whex})
            except Exception:
                pass
            return True
        try:
            return bool(self.client.call(
                {"op": "cancel_object", "obj": obj_hex, "force": force}))
        except Exception:
            return False

    # ------------------------------------------------------------------
    # Objects
    def object_future(self, obj_hex: str) -> Future:
        return self.object_futures([obj_hex])[0]

    def object_futures(self, obj_hexes: Sequence[str]) -> List[Future]:
        """Batch variant: ONE subscribe message for all new hexes (a
        get() of N refs used to cost N control messages).  Owner-direct
        actor results resolve from local futures — no head subscribe."""
        if self._pending_count:
            self._flush_direct_sends()
        futs: List[Future] = []
        new: List[str] = []
        created: List[tuple] = []
        with self._lock:
            for obj_hex in obj_hexes:
                fut = self._direct_futures.get(obj_hex)
                if fut is not None:
                    futs.append(fut)
                    continue
                fut = self._object_futures.get(obj_hex)
                if fut is None:
                    fut = Future()
                    self._object_futures[obj_hex] = fut
                    created.append((obj_hex, fut))
                futs.append(fut)
                if obj_hex not in self._subscribed:
                    self._subscribed.add(obj_hex)
                    new.append(obj_hex)
            if new:
                self.client.send({"op": "subscribe_objects", "objs": new})
        for obj_hex, fut in created:
            self._track_resolution(obj_hex, fut)
        return futs

    def _load_object(self, obj_hex: str, info: dict,
                     timeout: Optional[float] = None,
                     _attempt: int = 0,
                     _deadline: Optional[float] = None) -> Any:
        # An explicit caller timeout is a TOTAL budget across every
        # refetch retry round, not per round: convert it to a deadline
        # once and hand each round the remainder.
        if timeout is not None and _deadline is None:
            _deadline = time.monotonic() + timeout
        if info.get("direct"):
            # Owner-direct actor result: the serialized bytes arrived on
            # the direct actor connection (never touched the head).
            return self._finish_load(obj_hex, info["data"], info)
        if info.get("inline") is not None:
            data = info["inline"]
        elif info.get("in_shm"):
            if self.store is None:
                # Thin client: the server reads the shm payload for us.
                # with_meta: the error flag must come from the same
                # snapshot as the payload — the object may have become an
                # ObjectLostError after this client cached `info`.
                reply = self.client.call({"op": "fetch_object",
                                          "obj": obj_hex,
                                          "with_meta": True})
                if reply is None or reply.get("data") is None:
                    raise RuntimeError(
                        f"object {obj_hex} no longer available")
                return self._finish_load(
                    obj_hex, reply["data"],
                    {**info, "is_error": reply["is_error"]})
            try:
                seg = self.store.attach(ObjectID.from_hex(obj_hex),
                                        info["size"])
            except Exception as e:  # noqa: BLE001
                if info.get("node", "head") != self.store_node:
                    # Not in this node's arena (and no cached replica):
                    # pull the bytes from the holding node over the
                    # object plane (reference ObjectManager Pull,
                    # object_manager.h:139) and cache them locally.
                    try:
                        data = self._pull_remote_object(obj_hex, info)
                        return self._finish_load(obj_hex, data, info)
                    except Exception:
                        if _attempt >= 3:
                            raise
                        # Node dead or its arena evicted the copy: tell
                        # the head (it verifies and kicks lineage
                        # reconstruction), then re-subscribe for the
                        # recovered value.
                        try:
                            self.client.call(
                                {"op": "report_object_lost",
                                 "obj": obj_hex}, timeout=30.0)
                        except Exception:
                            pass
                        e = FileNotFoundError(obj_hex)
                # Stale location: the server may have SPILLED the object
                # after this client cached its in-shm info. Drop the
                # cached future + subscription and re-subscribe — the
                # server restores spilled objects on subscribe.  Bounded
                # RETRIES, not one shot: under arena pressure a
                # lineage-reconstructed value can get spilled again
                # between the server's publish and our attach, and one
                # more subscribe round is the correct response.
                if _attempt >= 3 or not _is_missing_segment_error(e):
                    raise
                fut = self._refetch_object(obj_hex)
                try:
                    # Honor an explicit caller deadline fully; for
                    # timeout=None gets, bound the wait generously (a
                    # truly freed object's fresh subscription would stay
                    # PENDING forever, but slow external-storage restores
                    # must be allowed to finish).
                    info2 = fut.result(
                        timeout=max(_deadline - time.monotonic(), 0.1)
                        if _deadline is not None else 300.0)
                except (TimeoutError, _FutureTimeoutError):
                    raise GetTimeoutError(
                        f"timed out refetching {obj_hex}") from None
                return self._load_object(obj_hex, info2,
                                         _attempt=_attempt + 1,
                                         _deadline=_deadline)
            if info.get("node", "head") != self.store_node:
                # Primary copy lives elsewhere but attach succeeded:
                # a previously pulled replica served this read from shm.
                object_plane.OBJ._inc("arena_cache_hits")
            data = seg.buf[: info["size"]]
        else:
            raise RuntimeError(f"object {obj_hex} ready but has no payload")
        return self._finish_load(obj_hex, data, info)

    def _finish_load(self, obj_hex: str, data, info: dict) -> Any:
        # Collect borrow increfs for every ref inside the value into ONE
        # control message (a get() of an object holding 10k refs used to
        # cost 10k sends).
        self._tls.incref_buf = buf = []
        try:
            value = serialization.deserialize(
                data, ref_deserializer=self._on_ref_deser)
        finally:
            self._tls.incref_buf = None
            # Send whatever was buffered even if deserialize raised
            # partway: the already-constructed refs will decref on GC,
            # and uncovered increfs would underflow head refcounts.
            if buf:
                try:
                    self.client.send({"op": "incref_batch", "objs": buf})
                except Exception:
                    pass
        if info.get("is_error"):
            raise value
        return value

    def _node_conn(self, address: str) -> rpc.Client:
        """Connection to another node's object server (cached).  The dial
        happens OUTSIDE self._lock — a dead node's connect retries must
        not stall this process's object subscription path."""
        with self._lock:
            conn = self._node_conns.get(address)
        if conn is not None and not conn._closed:
            return conn
        conn = rpc.Client(address, connect_timeout=5.0)
        with self._lock:
            existing = self._node_conns.get(address)
            if existing is not None and not existing._closed:
                conn.close()
                return existing
            self._node_conns[address] = conn
        return conn

    def _nm_pull(self, obj_hex: str, size: int, addr: str):
        """Route a remote fetch through this host's node manager
        (RAY_TPU_LOCAL_NM, set for spawned workers): the NM single-
        flights per object at NODE level, so two workers on one host
        never pull the same object over the wire twice — the bytes land
        once in the shared arena and both read it via attach().
        Returns the payload view on success, None to fall back to the
        direct per-process pull (driver processes, RAY_TPU_NM_PULL=0,
        arena-full degradation, NM errors)."""
        if self.store is None:
            return None
        nm_addr = os.environ.get("RAY_TPU_LOCAL_NM", "")
        if not nm_addr or os.environ.get(
                "RAY_TPU_NM_PULL", "1").strip().lower() in (
                "0", "false", "no", "off"):
            return None
        try:
            nm = self._node_conn(nm_addr)
            r = nm.call({"op": "pull_object", "obj": obj_hex,
                         "size": size, "addr": addr}, timeout=150.0)
            if not (r and r.get("cached")):
                return None  # NM degraded to uncached — pull directly
            view = self.store.attach(ObjectID.from_hex(obj_hex), size)
            return view.buf[:size]
        except Exception:  # raylint: allow-swallow(NM pull is best-effort; caller falls back to a direct pull)
            return None

    def _pull_remote_object(self, obj_hex: str, info: dict):
        """Windowed chunked pull of an object living in another node's
        arena (reference ObjectManager chunked transfer via
        object_buffer_pool).  addr == "" means the head arena: chunks
        ride the control client.  Chunks land directly in a pre-created
        local arena segment (no intermediate full-size buffer) so later
        readers on this node hit shm, and concurrent pulls of the same
        object in this process coalesce onto one wire transfer
        (object_plane.PullManager)."""

        def _do_pull():
            # One-way announce BEFORE the transfer: the head credits
            # this node in the locality tie-break while the pull is in
            # flight (gcs._locality_bytes "pulling" credit), so a task
            # chasing this object can land here instead of triggering a
            # second transfer elsewhere.  Best-effort; the
            # object_replica announce below supersedes it on landing.
            try:
                self.client.send(
                    {"op": "object_pull_started", "obj": obj_hex})
            except Exception:
                pass
            size = info["size"]
            addr = info.get("addr", "")
            nm_data = self._nm_pull(obj_hex, size, addr)
            if nm_data is not None:
                return nm_data
            client = self._node_conn(addr) if addr else self.client
            data, cached = object_plane.pull_into_store(
                client, self.store, obj_hex, size,
                self.config.transfer_chunk_bytes,
                window=self.config.pull_window, timeout=120.0)
            if cached:
                # Tell the directory about the replica so a cluster-wide
                # free deletes this arena's copy too (no leak on
                # consumer nodes).
                self.client.send({"op": "object_replica", "obj": obj_hex})
            return data

        return self._pull_manager.pull(obj_hex, _do_pull, timeout=150.0)

    def forget_object(self, obj_hex: str):
        """Retire a speculative subscription (a stream-item probe for an
        index the stream ended before): drop the local future and tell
        the directory to delete the PENDING placeholder if nothing else
        references it — otherwise every consumed stream leaks one
        entry on the head and one future here."""
        with self._lock:
            self._object_futures.pop(obj_hex, None)
            self._subscribed.discard(obj_hex)
        self._resolved.discard(obj_hex)
        try:
            self.client.send({"op": "forget_object", "obj": obj_hex})
        except Exception:
            pass

    def _refetch_object(self, obj_hex: str) -> Future:
        """Forget the resolved location of an object and subscribe again
        (used when a cached in-shm location went stale via spilling or
        loss)."""
        with self._lock:
            self._object_futures.pop(obj_hex, None)
            self._subscribed.discard(obj_hex)
            # A stale DIRECT future must go too: object_futures prefers
            # it, so leaving it would replay the dead location forever
            # (oversized direct results resolve to an in_shm pointer).
            fut = self._direct_futures.get(obj_hex)
            if fut is not None and fut.done():
                self._direct_futures.pop(obj_hex, None)
        self._resolved.discard(obj_hex)
        return self.object_future(obj_hex)

    def _on_ref_deser(self, ref: ObjectRef):
        # A ref arrived inside a deserialized value: register a borrow so the
        # owner keeps the object alive while this process holds the ref
        # (reference borrowing protocol, reference_count.h).
        buf = getattr(self._tls, "incref_buf", None)
        if buf is not None:
            buf.append(ref.hex())
            return
        try:
            self.client.send({"op": "incref", "obj": ref.hex()})
        except Exception:
            pass

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None):
        futs = self.object_futures([r.hex() for r in refs])
        deadline = None if timeout is None else time.monotonic() + timeout
        results = []
        for r, fut in zip(refs, futs):
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(f"get() timed out on {r}")
            try:
                info = fut.result(timeout=remaining)
            except (TimeoutError, _FutureTimeoutError):
                # Both spellings: concurrent.futures.TimeoutError only
                # became the builtin TimeoutError in Python 3.11.
                raise GetTimeoutError(f"get() timed out on {r}") from None
            remaining = None if deadline is None \
                else max(deadline - time.monotonic(), 0.1)
            results.append(self._load_object(r.hex(), info,
                                             timeout=remaining))
        return results

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        self._store_value(oid, value)
        return ObjectRef(oid, owner=self.worker_hex)

    def put_serialized(self, ser) -> "ObjectRef":
        """Store an already-serialized value without re-pickling it.

        The big-arg submit path serializes once to measure size; routing
        the resulting ``Serialized`` here (instead of ``put(value)``,
        which re-serializes from scratch) halves the CPU cost of every
        over-inline-threshold argument and lets pickle5 out-of-band
        buffers flow straight into the arena segment."""
        oid = ObjectID.from_random()
        for hex_id in ser.contained_refs:
            self._maybe_promote_direct(hex_id)
        self._store_serialized(oid, ser)
        return ObjectRef(oid, owner=self.worker_hex)

    def _serialize_for_ship(self, value: Any):
        """Serialize a value that is leaving this process, promoting any
        direct-owned refs it contains so remote holders can resolve them."""
        ser = serialization.serialize(value)
        for hex_id in ser.contained_refs:
            self._maybe_promote_direct(hex_id)
        return ser

    def _store_value(self, oid: ObjectID, value: Any, is_error: bool = False):
        ser = self._serialize_for_ship(value)
        return self._store_serialized(oid, ser, is_error=is_error)

    def _store_serialized(self, oid: ObjectID, ser, is_error: bool = False,
                          lineage_spec=None):
        with self._lock:
            self._local_known.add(oid.hex())
        size = ser.total_bytes
        # Thin clients ship everything inline over the connection (bounded
        # only by the rpc frame limit); full clients inline small objects
        # and put the rest in shm.
        if self.store is None:
            if size > self.config.rpc_max_message_bytes:
                raise ValueError(
                    f"object of {size} bytes exceeds the thin client's "
                    f"message limit ({self.config.rpc_max_message_bytes});"
                    " connect a full driver (ray_tpu.init(address=...)) "
                    "for shared-memory puts")
            inline_ok = True
        else:
            inline_ok = size <= self.config.max_inline_object_size
        if inline_ok:
            data = ser.to_bytes()
            if not is_error and size <= 64 * 1024:
                with self._lock:
                    prev = self._inline_cache.pop(oid.hex(), None)
                    if prev is not None:  # overwrite (retry/recon re-put)
                        self._inline_cache_bytes -= len(prev)
                    self._inline_cache[oid.hex()] = data
                    self._inline_cache_bytes += size
                    while self._inline_cache_bytes > 16 * 1024 * 1024 \
                            and self._inline_cache:
                        old, blob = next(iter(self._inline_cache.items()))
                        del self._inline_cache[old]
                        self._inline_cache_bytes -= len(blob)
            self._send_or_buffer({
                "op": "put_object", "obj": oid.hex(), "size": size,
                "inline": data, "is_error": is_error,
            })
        else:
            seg = self.store.create(oid, size)
            ser.write_into(seg.buf[:size])
            self.store.seal(oid)
            put = {
                "op": "put_object", "obj": oid.hex(), "size": size,
                "inline": None, "in_shm": True, "is_error": is_error,
            }
            if lineage_spec is not None:
                put["lineage"] = lineage_spec
            self._send_or_buffer(put)

    def _send_or_buffer(self, msg: dict):
        buf = getattr(self._tls, "put_buffer", None)
        if buf is not None:
            buf.append(msg)
        else:
            # Ride the ordered coalescing queue: consecutive puts collapse
            # into one put_object_batch frame (head registers the run
            # under one lock hold), and ordering against later submits
            # that reference the object is preserved.  get()/wait()/
            # direct sends flush first, so visibility is unchanged.
            self._queue_for_flush("put", None, msg)

    def begin_put_batch(self):
        self._tls.put_buffer = []

    def take_put_batch(self) -> List[dict]:
        buf = getattr(self._tls, "put_buffer", None) or []
        self._tls.put_buffer = None
        return buf

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        """Readiness via the resolved-hex set (maintained by future
        done-callbacks): each call is set-membership over the refs plus
        a condition wait — no per-future lock traffic, so the classic
        pop-one-of-N polling loop is O(n) set lookups per call instead
        of O(n) future-lock acquisitions."""
        if self._pending_count:
            self._flush_direct_sends()
        resolved = self._resolved
        hexes = [r._hex for r in refs]
        # Refs this process doesn't track yet need futures/subscriptions
        # (and their done-callbacks feed the resolved set).
        with self._lock:
            untracked = [
                h for h in hexes
                if h not in resolved and h not in self._direct_futures
                and h not in self._object_futures]
        if untracked:
            self.object_futures(hexes)
        deadline = None if timeout is None else time.monotonic() + timeout
        # More returns than refs can never be satisfied — clamp so the
        # loop terminates once everything resolved (wait([]) included).
        num_returns = min(num_returns, len(hexes))
        if not hexes:
            return [], []

        def _first_idx():
            for i, h in enumerate(hexes):
                if h in resolved:
                    return i
            return -1

        if num_returns == 1:
            # The pop-one-of-N polling idiom: early-exit scan + C-speed
            # list slicing keep each call near O(position of first
            # resolved) instead of O(n) Python-level list building.
            with self._resolved_cond:
                while True:
                    idx = _first_idx()
                    if idx >= 0:
                        break
                    remaining = None if deadline is None else \
                        deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        break
                    if not self._resolved_cond.wait(timeout=remaining):
                        break
            if idx < 0:
                return [], list(refs)
            refs = list(refs)
            return [refs[idx]], refs[:idx] + refs[idx + 1:]

        with self._resolved_cond:
            while True:
                n_ready = sum(1 for h in hexes if h in resolved)
                if n_ready >= num_returns:
                    break
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                if not self._resolved_cond.wait(timeout=remaining):
                    break
        # Single-pass partition: resolved refs beyond num_returns stay
        # in not_ready, per wait() semantics.
        ready: List[ObjectRef] = []
        not_ready: List[ObjectRef] = []
        for r, h in zip(refs, hexes):
            if len(ready) < num_returns and h in resolved:
                ready.append(r)
            else:
                not_ready.append(r)
        return ready, not_ready

    def on_ref_deleted(self, object_id: ObjectID):
        """Runs from ObjectRef.__del__ — i.e. at ARBITRARY GC points,
        possibly while this thread holds runtime or socket locks.  It
        must only touch the RLock'd flush queue: the decref rides the
        ordered head queue (naturally AFTER the submit that registered
        the object), and the background flusher ships it."""
        if self._closed:
            return
        obj_hex = object_id.hex()
        # Bare discard (no cond): set ops are GIL-atomic, and taking the
        # non-reentrant condition from a GC-triggered __del__ could
        # deadlock against a thread inside _mark_resolved.
        self._resolved.discard(obj_hex)
        with self._lock:
            self._local_known.discard(obj_hex)
            blob = self._inline_cache.pop(obj_hex, None)
            if blob is not None:
                self._inline_cache_bytes -= len(blob)
            if obj_hex in self._direct_futures:
                self._direct_futures.pop(obj_hex, None)
                actor_hex = self._direct_actor_of.pop(obj_hex, "")
                self._direct_inflight.get(actor_hex, set()).discard(obj_hex)
                # Never promoted → the head has no entry: purely local
                # cleanup, zero control messages for the whole call.
                if obj_hex not in self._direct_promoted:
                    return
                self._direct_promoted.discard(obj_hex)
        try:
            self._queue_for_flush("decref", None, obj_hex, from_del=True)
        except Exception:
            pass

    # ------------------------------------------------------------------
    # Task submission
    def _prepare_args(self, args: Sequence[Any], borrows: List[str]):
        out: List[TaskArg] = []
        for a in args:
            if isinstance(a, ObjectRef):
                cached = self._inline_cache.get(a.hex())
                if cached is not None:
                    # Hydrate: the executor gets the value inline — no
                    # borrow, no incref, no fetch round trips (top-level
                    # ref args resolve to values either way).
                    out.append(TaskArg(is_ref=False, data=cached))
                    continue
                self._maybe_promote_direct(a.hex())
                borrows.append(a.hex())
                # Queued (not sent): the submit that registered this ref
                # may itself still be in the flush queue — the incref
                # must reach the head AFTER it or it no-ops.
                self._queue_for_flush("incref", None, a.hex())
                out.append(TaskArg(is_ref=True, object_hex=a.hex()))
            else:
                ser = serialization.serialize(a)
                for hex_id in ser.contained_refs:
                    self._maybe_promote_direct(hex_id)
                    borrows.append(hex_id)
                    self._queue_for_flush("incref", None, hex_id)
                if ser.total_bytes > self.config.max_inline_object_size:
                    # Reuse the serialization we just produced: put(a)
                    # would pickle the arg a second time (and memcpy its
                    # buffers twice for a 64 MiB array).
                    ref = self.put_serialized(ser)
                    borrows.append(ref.hex())
                    # Same ordered queue as the put itself: a direct send
                    # would reach the head BEFORE the buffered put_object
                    # (no-op incref), and the temp ref's __del__ decref —
                    # also queued — would then free the fresh object.
                    self._queue_for_flush("incref", None, ref.hex())
                    out.append(TaskArg(is_ref=True, object_hex=ref.hex()))
                else:
                    out.append(TaskArg(is_ref=False, data=ser.to_bytes()))
        return out

    def ensure_func(self, func_id: str, blob: bytes) -> Optional[bytes]:
        """Upload the function blob once per session; return None if cached."""
        if func_id in self._sent_funcs:
            return None
        self.client.send({"op": "put_func", "func_id": func_id, "blob": blob})
        self._sent_funcs.add(func_id)
        return None

    def fetch_func(self, func_id: str) -> Optional[bytes]:
        return self.client.call({"op": "get_func", "func_id": func_id})

    def _prepare_runtime_env(self, runtime_env: Optional[dict]
                             ) -> Optional[dict]:
        """Package local working_dir/py_modules into content-addressed
        pkg:// KV uploads (runtime_env/packaging.py) so the env dict that
        ships — and keys the worker pool — is location-independent."""
        if not runtime_env:
            return runtime_env
        from ray_tpu.runtime_env.packaging import prepare_runtime_env

        return prepare_runtime_env(runtime_env, self.client.call)

    @staticmethod
    def _split_strategy(scheduling_strategy):
        """Extract (pg_hex, bundle_index, residual_strategy).

        PlacementGroupSchedulingStrategy becomes spec fields (the scheduler
        keys on them); other strategies ship as-is."""
        if scheduling_strategy is None:
            return "", -1, None
        if type(scheduling_strategy).__name__ == \
                "PlacementGroupSchedulingStrategy":
            pg = scheduling_strategy.placement_group
            return (pg._pg_hex,
                    scheduling_strategy.placement_group_bundle_index, None)
        return "", -1, scheduling_strategy

    def submit_task(self, func_id: str, func_blob: bytes, args: Sequence[Any],
                    num_returns, resources: Dict[str, float],
                    max_retries: int, name: str = "",
                    runtime_env: Optional[dict] = None,
                    scheduling_strategy=None):
        """Returns a list of ObjectRefs, or an ObjectRefGenerator when
        num_returns == "streaming" (core/streaming.py)."""
        from ray_tpu.core.streaming import STREAMING, ObjectRefGenerator

        streaming = num_returns == STREAMING
        borrows: List[str] = []
        task_args = self._prepare_args(args, borrows)
        self.ensure_func(func_id, func_blob)
        runtime_env = self._prepare_runtime_env(runtime_env)
        return_ids = [] if streaming else [
            ObjectID.from_random() for _ in range(num_returns)]
        pg_hex, bundle_index, scheduling_strategy = self._split_strategy(
            scheduling_strategy)
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            func_id=func_id,
            func_blob=None,
            args=task_args,
            num_returns=0 if streaming else num_returns,
            return_ids=return_ids,
            resources=resources,
            max_retries=max_retries,
            name=name,
            owner=self.worker_hex,
            runtime_env=runtime_env,
            scheduling_strategy=scheduling_strategy,
            placement_group_hex=pg_hex,
            bundle_index=bundle_index,
            borrows=borrows,
            is_streaming=streaming,
            trace_ctx=_make_trace_ctx(),
        )
        if self._lease_eligible(spec):
            # Owner-direct lease path: the head never sees this task
            # (reference direct task transport).
            self._submit_via_lease(spec)
        else:
            self._queue_for_flush("submit", None, spec)
        if streaming:
            return ObjectRefGenerator(spec.task_id)
        return [ObjectRef(oid, owner=self.worker_hex) for oid in return_ids]

    # ------------------------------------------------------------------
    # Actors
    def create_actor(self, class_id: str, class_blob: bytes,
                     args: Sequence[Any], resources: Dict[str, float],
                     max_restarts: int, name: str, namespace: str,
                     max_concurrency: int,
                     max_task_retries: int = 0,
                     concurrency_groups: Optional[Dict[str, int]] = None,
                     runtime_env: Optional[dict] = None,
                     scheduling_strategy=None) -> ActorID:
        borrows: List[str] = []
        task_args = self._prepare_args(args, borrows)
        self.ensure_func(class_id, class_blob)
        runtime_env = self._prepare_runtime_env(runtime_env)
        actor_id = ActorID.from_random()
        pg_hex, bundle_index, scheduling_strategy = self._split_strategy(
            scheduling_strategy)
        spec = ActorCreationSpec(
            actor_id=actor_id,
            class_id=class_id,
            class_blob=None,
            args=task_args,
            resources=resources,
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            name=name,
            namespace=namespace,
            max_concurrency=max_concurrency,
            concurrency_groups=concurrency_groups or None,
            owner=self.worker_hex,
            runtime_env=runtime_env,
            scheduling_strategy=scheduling_strategy,
            placement_group_hex=pg_hex,
            bundle_index=bundle_index,
        )
        self.client.send({"op": "create_actor", "spec": spec})
        self.client.send({"op": "subscribe_actor", "actor": actor_id.hex()})
        with self._actor_cv:
            self._actor_queues.setdefault(actor_id.hex(), [])
        return actor_id

    def subscribe_actor(self, actor_hex: str):
        with self._actor_cv:
            if actor_hex not in self._actor_state:
                self.client.send({"op": "subscribe_actor", "actor": actor_hex})
                self._actor_queues.setdefault(actor_hex, [])

    def actor_state(self, actor_hex: str) -> Optional[dict]:
        with self._actor_cv:
            return self._actor_state.get(actor_hex)

    def wait_actor_alive(self, actor_hex: str, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._actor_cv:
            while True:
                st = self._actor_state.get(actor_hex)
                if st is not None and st["state"] in ("ALIVE", "DEAD"):
                    return st
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"actor {actor_hex} not alive in time")
                self._actor_cv.wait(timeout=remaining)

    def submit_actor_task(self, actor_hex: str, method_name: str,
                          args: Sequence[Any], num_returns,
                          name: str = ""):
        """num_returns may be "streaming": the method is a generator and
        each yield becomes its own object (core/streaming.py), returned
        as an ObjectRefGenerator — the streaming-response path serve's
        ingress uses for token streams."""
        from ray_tpu.core.streaming import (
            STREAMING,
            ObjectRefGenerator,
            stream_eos_id,
        )

        streaming = num_returns == STREAMING
        borrows: List[str] = []
        task_args = self._prepare_args(args, borrows)
        task_id = TaskID.from_random()
        return_ids = [] if streaming else [
            ObjectID.from_random() for _ in range(num_returns)]
        # Plain single-return calls take the owner-direct path: the
        # result rides the direct actor connection back and the head
        # never sees the call (reference: direct actor transport — GCS
        # uninvolved — plus the in-process store for small returns).
        direct = not streaming and num_returns == 1
        if direct:
            self._register_direct(return_ids[0].hex(), actor_hex)
        else:
            # Register returns under the actor so its death fails
            # waiters; for streams that role falls to the EOS object.
            reg = [stream_eos_id(task_id).hex()] if streaming else \
                [oid.hex() for oid in return_ids]
            self.client.send({
                "op": "register_objects",
                "objs": reg,
                "actor": actor_hex,
            })
        spec = TaskSpec(
            task_id=task_id,
            func_id="", func_blob=None,
            args=task_args,
            num_returns=0 if streaming else num_returns,
            return_ids=return_ids,
            resources={},
            owner=self.worker_hex,
            actor_id=ActorID.from_hex(actor_hex),
            method_name=method_name,
            name=name or method_name,
            borrows=borrows,
            is_streaming=streaming,
            direct=direct,
            trace_ctx=_make_trace_ctx(),
        )
        self._route_actor_task(actor_hex, spec)
        if streaming:
            return ObjectRefGenerator(spec.task_id)
        return [ObjectRef(oid, owner=self.worker_hex) for oid in return_ids]

    def _route_actor_task(self, actor_hex: str, spec: TaskSpec):
        with self._actor_cv:
            st = self._actor_state.get(actor_hex)
            if st is None or st["state"] in ("PENDING_CREATION", "RESTARTING"):
                self._actor_queues.setdefault(actor_hex, []).append(spec)
                if st is None:
                    self.client.send(
                        {"op": "subscribe_actor", "actor": actor_hex})
                return
            if st["state"] == "DEAD":
                self._fail_actor_task(spec, st.get("reason", "actor dead"))
                return
            address = st["address"]
        self._send_actor_task(actor_hex, address, spec)

    def _actor_conn(self, address: str) -> rpc.Client:
        with self._lock:
            conn = self._actor_conns.get(address)
            if conn is not None:
                return conn
        # Dial outside the lock; on_push carries owner-direct results.
        conn = rpc.Client(
            address, on_push=self._on_direct_push,
            on_disconnect=lambda: self._on_direct_conn_lost(address))
        with self._lock:
            existing = self._actor_conns.get(address)
            if existing is not None:
                conn.close()
                return existing
            self._actor_conns[address] = conn
        return conn

    def _on_direct_conn_lost(self, address: str):
        """A direct (actor / leased-worker) connection dropped.  Actor
        callers recover via the head's actor_update pushes; lease
        workers are the owner's to fail over."""
        if self._closed:
            return
        with self._lock:
            conn = self._actor_conns.get(address)
            if conn is not None and conn._closed:
                self._actor_conns.pop(address, None)
        with self._lease_lock:
            whexes = list(self._lease_addr_workers.get(address, ()))
        for whex in whexes:
            self._on_lease_worker_lost(whex, "connection lost")

    def _send_actor_task(self, actor_hex: str, address: str, spec: TaskSpec):
        # One persistent flusher per client (not a timer per burst:
        # thread spawns cost more than the flush).  It is the
        # fire-and-forget safety net; the common case is the submitting
        # thread flushing at its next get()/wait().
        self._queue_for_flush("direct", address, spec)

    def _flush_if_pending(self):
        if self._pending_count:
            self._flush_direct_sends()
        if getattr(self, "_lease_request_pending", False):
            self._send_lease_requests()

    def _ensure_flusher(self):
        start = False
        with self._send_lock:
            if not self._flusher_started:
                self._flusher_started = True
                start = True
        if start:
            threading.Thread(target=self._send_flusher,
                             name="direct-send-flush",
                             daemon=True).start()

    def _send_flusher(self):
        while not self._closed:
            # With live leases the flusher doubles as the idle-lease
            # sweeper (bounded wait); otherwise it parks until woken.
            self._flush_ev.wait(timeout=0.1 if self._leases else None)
            self._flush_ev.clear()
            time.sleep(0.002)
            try:
                self._pump_deferred_pools()
                self._flush_direct_sends()
                self._send_lease_requests()
                if self._leases:
                    self._sweep_idle_leases()
            except Exception:
                # The flusher is the fire-and-forget safety net; it must
                # survive transient send failures (head restart window).
                time.sleep(0.05)

    def _queue_for_flush(self, kind: str, key, item, from_del=False):
        """Shared enqueue for coalesced control sends (actor tasks, head
        submits, borrow increfs and ref-deletion decrefs — refcount ops
        must stay ORDERED after the submits that register their
        objects); flushed by get()/wait(), the 64-item cap, or the 2 ms
        flusher.  Safe to re-enter from __del__ (pure queue appends
        under an RLock; the flusher thread start happens outside)."""
        start_flusher = False
        with self._send_lock:
            if kind == "direct":
                self._pending_direct.setdefault(key, []).append(item)
            elif kind == "pool":
                self._pending_pool.setdefault(key, []).append(item)
            else:
                self._pending_submits.append((kind, item))
            self._pending_count += 1
            count = self._pending_count
            if not self._flusher_started:
                self._flusher_started = True
                start_flusher = True
        if start_flusher:
            threading.Thread(target=self._send_flusher,
                             name="direct-send-flush",
                             daemon=True).start()
        if count >= 64 and not from_del:
            self._flush_direct_sends()
        else:
            # from_del: never flush inline — the interrupted frame may
            # be inside the rpc client's (non-reentrant) socket lock.
            self._flush_ev.set()

    def _flush_direct_sends(self):
        with self._flush_mutex:
            self._flush_direct_sends_locked()

    def _flush_direct_sends_locked(self):
        with self._send_lock:
            if self._pending_count == 0:
                return
            pending, self._pending_direct = self._pending_direct, {}
            pool_sends, self._pending_pool = self._pending_pool, {}
            submits, self._pending_submits = self._pending_submits, []
            self._pending_count = 0
        if submits:
            sent_upto = 0
            try:
                for end, msg in self._head_frames(submits):
                    self.client.send(msg)
                    sent_upto = end
            except Exception:
                # Head connection down mid-flush (restart window): put
                # back ONLY the unsent tail (re-queuing sent frames
                # would double-execute tasks) and arm the flusher so
                # the retry happens even if no further get()/call()
                # ever fires.
                rest = submits[sent_upto:]
                if rest:
                    with self._send_lock:
                        self._pending_submits = rest + self._pending_submits
                        self._pending_count += len(rest)
                    self._flush_ev.set()
        for address, specs in pending.items():
            try:
                conn = self._actor_conn(address)
                # Mark delivered BEFORE the send: a fast direct_result
                # reply must find the inflight entry already present
                # (resolving discards it; marking after the send could
                # re-add an already-resolved object).
                for spec in specs:
                    self._mark_direct_delivered(spec)
                if len(specs) == 1:
                    conn.send({"op": "actor_task", "spec": specs[0]})
                else:
                    conn.send({"op": "actor_task_batch", "specs": specs})
            except Exception as e:  # connection refused: actor just died
                for spec in specs:
                    self._fail_actor_task(spec, f"cannot reach actor: {e}")
        for address, specs in pool_sends.items():
            try:
                conn = self._actor_conn(address)
                if len(specs) == 1:
                    conn.send({"op": "pool_task", "spec": specs[0]})
                else:
                    conn.send({"op": "pool_task_batch", "specs": specs})
            except Exception:
                # Leased worker unreachable: the per-worker loss path
                # retries/fails each in-flight spec.
                lost = set()
                with self._lease_lock:
                    for spec in specs:
                        ent = self._lease_of_obj.get(
                            spec.return_ids[0].hex())
                        if ent is not None:
                            lost.add(ent[1])
                for whex in lost:
                    self._on_lease_worker_lost(whex, "connection lost")

    @staticmethod
    def _head_frames(items):
        """Yield (end_index, frame_msg) for queued head messages,
        preserving enqueue order: runs of consecutive submits collapse
        into submit_task_batch frames, runs of increfs into
        incref_batch frames.  When wire batching is on, adjacent
        incref/decref runs additionally collapse into ONE refcount_delta
        vector of net per-object counts — no other message can land
        between entries of one run, so netting inside it is order-safe
        (a transient +1/-1 pair can never drive a live object to zero
        mid-run on the head)."""
        merge_refs = rpc.batching_enabled()
        i, n = 0, len(items)
        while i < n:
            kind = items[i][0]
            is_ref = kind in ("incref", "decref")
            j = i
            while j < n and (items[j][0] == kind or
                             (merge_refs and is_ref and
                              items[j][0] in ("incref", "decref"))):
                j += 1
            if is_ref and merge_refs and j - i > 1:
                deltas: Dict[str, int] = {}
                for k, obj_hex in items[i:j]:
                    deltas[obj_hex] = deltas.get(obj_hex, 0) + (
                        1 if k == "incref" else -1)
                deltas = {h: d for h, d in deltas.items() if d}
                if deltas:
                    yield j, {"op": "refcount_delta", "deltas": deltas}
                # All-zero net: drop the frame entirely (re-processing
                # on a retry is harmless — the net is still zero).
                i = j
                continue
            run = [it for _, it in items[i:j]]
            if kind == "submit":
                msg = {"op": "submit_task", "spec": run[0]} \
                    if len(run) == 1 else \
                    {"op": "submit_task_batch", "specs": run}
            elif kind == "task_event":
                # Delta-compress the run: multiple lifecycle events for
                # one task inside a flush window (RECEIVED+RUNNING+
                # FINISHED of a fast task) merge into one dict — later
                # events overlay earlier keys, first-seen order kept.
                merged: Dict[str, dict] = {}
                order: List[str] = []
                for ev in run:
                    tid = ev.get("task_id", "")
                    cur = merged.get(tid)
                    if cur is None:
                        merged[tid] = dict(ev)
                        order.append(tid)
                    else:
                        cur.update(ev)
                msg = {"op": "task_events",
                       "events": [merged[t] for t in order]}
            elif kind == "profile_report":
                # Resource samples are point-in-time state, not deltas:
                # a backlogged run collapses to the NEWEST sample (one
                # flusher per worker, so within-run order is sample
                # order and latest wins).
                msg = {"op": "profile_report", "sample": run[-1]}
            elif kind == "put":
                msg = run[0] if len(run) == 1 else \
                    {"op": "put_object_batch", "items": run}
            elif kind == "incref":
                msg = {"op": "incref", "obj": run[0]} \
                    if len(run) == 1 else \
                    {"op": "incref_batch", "objs": run}
            else:  # decref (ref deletions ride the same ordered queue)
                msg = {"op": "decref", "obj": run[0]} \
                    if len(run) == 1 else \
                    {"op": "decref_batch", "objs": run}
            yield j, msg
            i = j

    def _flush_actor_queue(self, actor_hex: str, address: str):
        with self._actor_cv:
            queue = self._actor_queues.get(actor_hex, [])
            self._actor_queues[actor_hex] = []
        for spec in queue:
            self._send_actor_task(actor_hex, address, spec)

    def _fail_actor_queue(self, actor_hex: str, reason: str):
        with self._actor_cv:
            queue = self._actor_queues.pop(actor_hex, [])
        for spec in queue:
            self._fail_actor_task(spec, reason)

    def _fail_actor_task(self, spec: TaskSpec, reason: str):
        err = ActorDiedError(spec.actor_id, reason)
        if getattr(spec, "is_streaming", False):
            # Streams have no pre-registered returns: fail the
            # end-of-stream object so iteration raises.
            from ray_tpu.core.streaming import stream_eos_id

            self._store_value(stream_eos_id(spec.task_id), err,
                              is_error=True)
            return
        if getattr(spec, "direct", False):
            for oid in spec.return_ids:
                self._fail_direct(oid.hex(), err)
            return
        for oid in spec.return_ids:
            self._store_value(oid, err, is_error=True)

    def kill_actor(self, actor_hex: str, no_restart: bool = True):
        self._flush_direct_sends()  # queued calls precede the kill
        self.client.send({"op": "kill_actor", "actor": actor_hex,
                          "no_restart": no_restart})

    def get_named_actor(self, name: str, namespace: str = "") -> Optional[dict]:
        return self.client.call({"op": "get_named_actor", "name": name,
                                 "namespace": namespace})

    # ------------------------------------------------------------------
    def close(self):
        try:
            self._flush_direct_sends()
        except Exception:
            pass
        try:
            self._release_all_leases()
        except Exception:
            pass
        self._closed = True
        # Wake the send flusher so it observes _closed and exits — a
        # flusher parked in wait() forever leaked one thread per
        # init/shutdown cycle (hundreds across a long test session).
        self._flush_ev.set()
        try:
            from ray_tpu.util import metrics

            metrics.unpublish(self.client.call, self.worker_hex)
        except Exception:
            pass
        for conn in self._actor_conns.values():
            conn.close()
        for conn in self._node_conns.values():
            conn.close()
        self.client.close()


def func_content_id(blob: bytes) -> str:
    return hashlib.sha1(blob).hexdigest()
