"""Resource model: named resource vectors with fractional amounts.

Counterpart of the reference's ResourceSet / NodeResources
(src/ray/common/scheduling/cluster_resource_data.h) with FixedPoint
arithmetic (fixed_point.h): amounts are stored as integer ten-thousandths so
fractional resources (0.5 CPU) compose exactly.

TPU-native extension (SURVEY.md §2 directive for N10): ``TPU`` is a
first-class resource alongside CPU/memory, and nodes may expose ICI-topology
markers (``TPU-v5e-8-head``, slice labels) the scheduler uses for
slice-aware placement, generalizing the reference's Python-side TPU
accelerator manager (python/ray/_private/accelerators/tpu.py).
"""

from __future__ import annotations

from typing import Dict, Mapping

GRANULARITY = 10_000  # fixed-point denominator

CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"


def _to_fixed(amount: float) -> int:
    return round(amount * GRANULARITY)


def _from_fixed(units: int) -> float:
    return units / GRANULARITY


class ResourceSet:
    """Immutable-ish mapping of resource name -> fixed-point amount."""

    __slots__ = ("_units",)

    def __init__(self, amounts: Mapping[str, float] | None = None, _units=None):
        if _units is not None:
            self._units: Dict[str, int] = {k: v for k, v in _units.items() if v > 0}
        else:
            self._units = {
                k: _to_fixed(v) for k, v in (amounts or {}).items() if v > 0
            }

    def to_dict(self) -> Dict[str, float]:
        return {k: _from_fixed(v) for k, v in self._units.items()}

    def get(self, name: str) -> float:
        return _from_fixed(self._units.get(name, 0))

    def is_empty(self) -> bool:
        return not self._units

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._units.get(k, 0) >= v for k, v in self._units.items())

    def fit_count(self, need: "ResourceSet") -> int:
        """How many disjoint copies of `need` fit inside this set."""
        if not need._units:
            return 1 << 30
        return min(self._units.get(k, 0) // v
                   for k, v in need._units.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        units = dict(self._units)
        for k, v in other._units.items():
            units[k] = units.get(k, 0) + v
        return ResourceSet(_units=units)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        units = dict(self._units)
        for k, v in other._units.items():
            units[k] = units.get(k, 0) - v
            if units[k] < 0:
                raise ValueError(
                    f"Resource {k} would go negative: {self.to_dict()} - {other.to_dict()}"
                )
        return ResourceSet(_units=units)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and other._units == self._units

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (ResourceSet, (self.to_dict(),))


def node_resources_from_env(num_cpus=None, num_tpus=None, extra=None) -> ResourceSet:
    """Detect this host's resources (CPU count, TPU chips if visible,
    accelerator pod-type markers like TPU-v4-16 / TPU-v4-16-head)."""
    import os

    amounts: Dict[str, float] = {}
    amounts[CPU] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    if num_tpus is None:
        num_tpus = detect_tpu_chips()
    if num_tpus:
        amounts[TPU] = float(num_tpus)
        try:
            from ray_tpu.accelerators import detect_additional_resources

            amounts.update(detect_additional_resources())
        except Exception:
            pass
    if extra:
        amounts.update(extra)
    return ResourceSet(amounts)


def visible_tpu_chip_ids() -> Optional[list]:
    """Chip ids assigned via env (TPU_VISIBLE_CHIPS / RAY_TPU_CHIPS),
    None when no env override is present.  Single source of the parsing
    shared by the scheduler (detect_tpu_chips) and the worker-facing
    get_accelerator_ids()."""
    import os

    env = os.environ.get("TPU_VISIBLE_CHIPS") or os.environ.get("RAY_TPU_CHIPS")
    if not env:
        return None  # unset/empty: caller falls back to device probing
    if env == "none":
        return []
    return [c for c in env.split(",") if c != ""]


def detect_tpu_chips() -> int:
    """Count locally visible TPU chips without initializing a JAX backend.

    Counterpart of the reference's TPU accelerator manager chip probing
    (python/ray/_private/accelerators/tpu.py:71): check the PCI accel
    device nodes and TPU_VISIBLE_CHIPS-style env overrides rather than
    importing jax (which would grab the chips).
    """
    import os

    ids = visible_tpu_chip_ids()
    if ids is not None:
        return len(ids)
    # vfio / accel device nodes on TPU VMs
    for pattern_dir, prefix in (("/dev", "accel"), ("/dev/vfio", "")):
        try:
            entries = os.listdir(pattern_dir)
        except OSError:
            continue
        n = len([e for e in entries if e.startswith(prefix) and e[len(prefix):].isdigit()])
        if n:
            return n
    # Under the axon tunnel there is exactly one chip but no device node;
    # honor an explicit platform hint instead of probing jax.
    if os.environ.get("JAX_PLATFORMS", "").startswith(("tpu", "axon")):
        return 1
    return 0
