"""Typed wire contract for the control-plane frame protocol.

Counterpart of the reference's proto IDL tier (src/ray/protobuf/*.proto
— the typed schemas every language speaks).  The framed RPC layer
(core/rpc.py) carries pickled dicts between Python peers and JSON dicts
for the cross-language door; this module is the SCHEMA for those
messages: one declarative table of every public op, its required and
optional fields with types, machine-checkable on both ends.

`validate(msg)` is cheap enough for ingress paths that accept untrusted
frames (the JSON door, the serve frame ingress); Python-internal paths
trust their own senders and skip it, exactly like generated proto
bindings trusting in-process construction.  `export_schema()` dumps the
contract as JSON for non-Python client generators (the C++ client's
hand-built frames can be checked against it in CI —
tests/test_cpp_client.py).

Field types: "str", "int", "float", "bool", "bytes", "list", "dict",
"any".  A trailing "?" marks the field optional.
"""

from __future__ import annotations

from typing import Any, Dict

# op -> {field: type_spec}
SCHEMA: Dict[str, Dict[str, str]] = {
    # -- registration / lifecycle --------------------------------------
    "register": {"worker_hex": "str", "pid": "int", "kind": "str",
                 "address": "str?", "env_key": "str?", "node_id": "str?"},
    "register_node": {"node_id": "str?", "resources": "dict",
                      "address": "str", "labels": "dict?",
                      "store_key": "str?", "shm_dir": "str?"},
    "worker_online": {},
    "ping": {},
    # -- objects -------------------------------------------------------
    "put_object": {"obj": "str", "size": "int", "inline": "bytes?",
                   "in_shm": "bool?", "is_error": "bool?"},
    "put_object_batch": {"items": "list"},
    "subscribe_objects": {"objs": "list", "grace": "bool?"},
    "subscribe_object": {"obj": "str", "grace": "bool?"},
    "fetch_object": {"obj": "str", "with_meta": "bool?"},
    "fetch_chunk": {"obj": "str", "size": "int", "offset": "int",
                    "length": "int"},
    # Node-to-node object plane (node_manager._handle): pull probe +
    # push-broadcast stream (core/object_plane.py PushManager).
    "has_object": {"obj": "str"},
    # Worker -> local node manager: single-flight a remote fetch into
    # this node's shared arena ({addr: ""} means the head's store).
    "pull_object": {"obj": "str", "size": "int", "addr": "str?"},
    "push_begin": {"obj": "str", "size": "int"},
    "push_chunk": {"obj": "str", "offset": "int", "data": "bytes"},
    "push_end": {"obj": "str"},
    "incref": {"obj": "str", "n": "int?"},
    "incref_batch": {"objs": "list"},
    "decref": {"obj": "str", "n": "int?"},
    "decref_batch": {"objs": "list"},
    # Coalesced net ref-count vector: {obj_hex: delta} with positive
    # deltas increfs and negative deltas decrefs (control-plane
    # micro-batching; runtime._head_frames → gcs._op_refcount_delta).
    "refcount_delta": {"deltas": "dict"},
    "free_objects": {"objs": "list"},
    "forget_object": {"obj": "str"},
    "object_replica": {"obj": "str"},
    "object_shm_info": {"obj": "str"},
    "report_object_lost": {"obj": "str"},
    # -- tasks ---------------------------------------------------------
    "submit_task": {"spec": "any"},
    "submit_task_batch": {"specs": "list"},
    "submit_named_task": {"name": "str", "args": "list?",
                          "num_cpus": "float?", "num_tpus": "float?",
                          "max_retries": "int?"},
    "task_done": {"task_id": "str", "failed": "bool?", "puts": "list?",
                  "decrefs": "list?"},
    "get_object_json": {"obj": "str"},
    "cancel_object": {"obj": "str", "force": "bool?"},
    "cancel_task": {"task": "str", "force": "bool?"},
    # -- C++-defined tasks/actors (cpp/include/ray_tpu/worker.h) -------
    "register_cpp_functions": {"functions": "list?",
                               "actor_classes": "list?"},
    "cpp_task_done": {"return": "str", "result": "any?", "error": "str?"},
    "create_cpp_actor": {"actor_class": "str", "args": "list?"},
    "list_cpp_functions": {},
    "submit_cpp_actor_task": {"instance": "str", "method": "str",
                              "args": "list?"},
    # -- worker leases (owner-direct task path) ------------------------
    "request_lease": {"token": "int?", "resources": "dict?",
                      "runtime_env": "dict?", "count": "int?"},
    "release_lease": {"workers": "list"},
    "kill_worker": {"worker": "str"},
    "task_events": {"events": "list"},
    # -- observability: span harvest / profiling / watchdog ------------
    # Head→worker pull of the worker's bounded span ring, cursor-based
    # and capped per reply (gcs._op_harvest_spans ↔ runtime._on_push).
    "collect_spans": {"token": "str", "cursor": "int", "limit": "int"},
    "collect_spans_result": {"token": "str", "cursor": "int",
                             "rows": "list", "missed": "int?",
                             "pid": "int?", "worker": "str?"},
    # Client→head: harvest every worker's ring (incremental, merged by
    # trace_id on the head) and return matching spans.
    "harvest_spans": {"trace_id": "str?", "max_spans": "int?",
                      "timeout_s": "float?", "since": "float?",
                      "poll": "bool?"},
    # Worker→head resource sample; rides the coalescing flusher
    # (runtime._head_frames collapses a run to the newest sample).
    "profile_report": {"sample": "dict"},
    "get_profile": {"samples": "bool?"},
    # Client→head: retune/toggle every worker's sampler at runtime
    # (bench_profiling.py's A/B switch).
    "set_profile_config": {"enabled": "bool?", "interval_s": "float?"},
    # One-way announce that a PullManager leader started pulling an
    # object to this node (locality tie-break credit in gcs._pick_node).
    "object_pull_started": {"obj": "str"},
    # -- functions -----------------------------------------------------
    "put_func": {"func_id": "str", "blob": "bytes"},
    "get_func": {"func_id": "str"},
    # -- actors --------------------------------------------------------
    "create_actor": {"spec": "any"},
    "subscribe_actor": {"actor": "str"},
    "actor_ready": {"actor": "str", "address": "str"},
    "actor_creation_failed": {"actor": "str", "reason": "str?"},
    "kill_actor": {"actor": "str", "no_restart": "bool?"},
    "get_named_actor": {"name": "str", "namespace": "str?"},
    "list_named_actors": {"namespace": "str?"},
    "register_objects": {"objs": "list", "actor": "str?"},
    # -- KV ------------------------------------------------------------
    # value: bytes from Python peers; the JSON door also takes plain
    # strings (the C++ client's convenience form, utf-8 at rest).
    "kv_put": {"key": "str", "value": "bytes|str", "overwrite": "bool?"},
    "kv_get": {"key": "str"},
    "kv_del": {"key": "str"},
    "kv_keys": {"prefix": "str?"},
    "kv_exists": {"key": "str"},
    # -- cluster / state -----------------------------------------------
    "cluster_resources": {},
    "available_resources": {},
    "list_tasks": {}, "list_actors": {}, "list_objects": {},
    "list_workers": {}, "list_nodes": {},
    "list_placement_groups": {},
    "add_node": {"resources": "dict", "node_id": "str?", "labels": "dict?"},
    "remove_node": {"node_id": "str"},
    # -- graceful drain (reference DrainRaylet / autoscaler DrainNode) --
    "drain_node": {"node_id": "str", "reason": "str?"},
    "drain_status": {"node_id": "str"},
    "objects_migrated": {"node_id": "str", "dest_node": "str",
                         "results": "dict"},
    "shutdown_cluster": {},
    "get_load": {},
    # -- placement groups ----------------------------------------------
    "create_pg": {"bundles": "list", "strategy": "str?", "name": "str?"},
    "remove_pg": {"pg": "str"},
    "pg_state": {"pg": "str"},
    # -- serve frame ingress (proxy.py FrameIngress) -------------------
    "serve_request": {"route": "str", "payload": "any?", "headers": "dict?"},
    # -- serve disaggregation (llm.py / llm_engine.py handoff) ---------
    # Prefill→decode KV handoff: the exported page bundle (k/v are
    # [L, n_ctx, page, KD] tensors; "done" short-circuits requests that
    # finished at prefill), the object-plane pointer it rides as, and
    # the hot-prefix digest replicas advertise for locality routing.
    # "trace" is the request-journey linkage [trace_id, span_id]: the
    # decode leg parents its spans under the prefill leg's replica
    # span, so a disaggregated request renders as ONE connected trace.
    "serve_kv_export": {"req": "int", "prompt": "list",
                        "generated": "list", "context_len": "int",
                        "page_size": "int", "num_layers": "int",
                        "kd": "int", "dtype": "str",
                        "chain_keys": "list?", "done": "list?",
                        "k": "any?", "v": "any?", "trace": "list?"},
    "serve_kv_import": {"obj": "str", "size": "int",
                        "trace": "list?"},
    "serve_prefix_digest": {"keys": "list"},
    # -- push / dispatch ops (head→client, head→node, owner→worker) ----
    # These ride Python-internal pickled frames, so runtime ingress
    # never validates them — but they are part of the wire contract all
    # the same, and raylint's conformance pass requires every op a
    # dispatch site handles to be declared here (and vice versa).
    # Task execution pushed to workers (worker._handle_direct /
    # runtime dispatch).
    "execute_task": {"spec": "any"},
    "pool_task": {"spec": "any"},
    "pool_task_batch": {"specs": "list"},
    "actor_task": {"spec": "any"},
    "actor_task_batch": {"specs": "list"},
    "cancel_pool_task": {"task": "str"},
    "create_actor_instance": {"spec": "any"},
    "exit": {},
    # Owner-direct result return (worker → submitting owner).
    "direct_result": {"obj": "str", "data": "bytes?", "is_error": "bool?"},
    "direct_result_batch": {"results": "list"},
    "direct_result_remote": {"obj": "str"},
    # Head→client object/actor/cluster notifications.
    "object_ready": {"obj": "str", "size": "int?", "inline": "bytes?",
                     "in_shm": "bool?", "is_error": "bool?",
                     "node": "str?", "addr": "str?"},
    "actor_update": {"actor": "str", "state": "str?", "address": "str?",
                     "reason": "str?", "max_task_retries": "int?"},
    "resource_view": {"seq": "int", "epoch": "str", "nodes": "any"},
    "cluster_view": {},
    "node_stats": {"stats": "dict"},
    # Head→owner lease protocol (the grant/revoke side of
    # request_lease/release_lease above).
    "lease_granted": {"token": "int", "workers": "list",
                      "denied": "bool?", "error": "str?"},
    "lease_revoked": {"worker": "str", "reason": "str?"},
    # Head→node worker lifecycle.
    "spawn_worker": {"worker_hex": "str", "kind": "str",
                     "env_key": "str?", "namespace": "str?",
                     "runtime_env": "dict?"},
    "worker_alive": {"worker_hex": "str"},
    "worker_spawn_failed": {"worker_hex": "str", "error": "str?"},
    "worker_setup_failed": {"env_key": "str", "error": "str?"},
    "get_runtime_env": {"env_key": "str"},
    # Object plane maintenance (head→node).
    "delete_object": {"obj": "str"},
    "object_info": {"obj": "str"},
    "migrate_objects": {"objects": "list", "dest": "str?",
                        "dest_node": "str?"},
    # Streaming generator consumer→head backpressure/free credit.
    "free_stream": {"task": "str", "from_index": "int",
                    "eos_consumed": "bool?", "count": "int?"},
    # Profiling / diagnostics.
    "profile": {"kind": "str", "token": "str?", "duration_s": "float?"},
    "profile_worker": {"worker_hex": "str", "kind": "str?",
                       "duration_s": "float?", "timeout_s": "float?"},
    "profile_result": {"token": "str", "data": "any?"},
    "profile_config": {"enabled": "bool?", "interval_s": "float?"},
    "flight_recorder": {"last": "int?", "since": "float?"},
}

_TYPES = {
    "str": str, "int": int, "float": (int, float), "bool": bool,
    "bytes": (bytes, bytearray), "list": (list, tuple), "dict": dict,
}


class SchemaError(ValueError):
    pass


def validate(msg: Any) -> None:
    """Raise SchemaError if msg is not a well-formed frame for its op.

    Unknown ops fail closed — an ingress accepting untrusted frames
    must not forward ops the contract doesn't name."""
    if not isinstance(msg, dict):
        raise SchemaError(f"frame must be a dict, got {type(msg).__name__}")
    op = msg.get("op")
    if not isinstance(op, str):
        raise SchemaError("frame missing string 'op'")
    fields = SCHEMA.get(op)
    if fields is None:
        raise SchemaError(f"unknown op {op!r}")
    for name, spec in fields.items():
        optional = spec.endswith("?")
        tname = spec.rstrip("?")
        if name not in msg or msg[name] is None:
            if optional:
                continue
            raise SchemaError(f"op {op!r} missing required field {name!r}")
        if tname == "any":
            continue
        expected = tuple(
            t for alt in tname.split("|")
            for t in (_TYPES[alt] if isinstance(_TYPES[alt], tuple)
                      else (_TYPES[alt],)))
        if not isinstance(msg[name], expected):
            raise SchemaError(
                f"op {op!r} field {name!r}: expected {tname}, got "
                f"{type(msg[name]).__name__}")
    extra = set(msg) - set(fields) - {"op"}
    if extra:
        raise SchemaError(f"op {op!r} has undeclared fields {sorted(extra)}")


def export_schema() -> Dict[str, Any]:
    """The contract as plain JSON (for non-Python client generators)."""
    return {"version": 1, "ops": SCHEMA}
