"""Log monitor: stream worker stdout/stderr to the driver.

Counterpart of the reference's per-node log monitor
(python/ray/_private/log_monitor.py: tails session/logs files, publishes
via GCS pubsub; drivers print with a `(pid=...)` prefix). Single-host
simplification: the driver tails the session log directory directly — no
pubsub hop — with the same worker-attribution prefix. Enabled by
`init(log_to_driver=True)` (the reference's default behavior).
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from typing import Dict, TextIO

_POLL_INTERVAL_S = 0.25
_WORKER_FILE = re.compile(r"worker-(?P<hex>[0-9a-f]+)\.(?P<stream>out|err)$")


class LogMonitor:
    """Tails `<session_dir>/logs/worker-*.{out,err}` and forwards new
    lines to the driver's stdout/stderr with a worker prefix."""

    def __init__(self, session_dir: str, out: TextIO = None,
                 err: TextIO = None):
        self.log_dir = os.path.join(session_dir, "logs")
        self.out = out or sys.stdout
        self.err = err or sys.stderr
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        # Serializes sweeps: stop()'s final flush can run concurrently
        # with the monitor thread's sweep.
        self._sweep_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="log-monitor")

    def start(self) -> "LogMonitor":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        # One final sweep so output produced right before shutdown lands.
        self._sweep()

    def _loop(self):
        while not self._stop.is_set():
            self._sweep()
            self._stop.wait(_POLL_INTERVAL_S)

    def _sweep(self):
        with self._sweep_lock:
            self._sweep_locked()

    def _sweep_locked(self):
        try:
            names = os.listdir(self.log_dir)
        except OSError:
            return
        for name in sorted(names):
            m = _WORKER_FILE.search(name)
            if not m:
                continue
            path = os.path.join(self.log_dir, name)
            offset = self._offsets.get(path, 0)
            # Binary IO with byte offsets: text-mode seek/read would count
            # characters and drift on multi-byte UTF-8.
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read()
            except OSError:
                continue
            if not chunk:
                continue
            # Emit only complete lines: a chunk can end mid-line (or even
            # mid-UTF-8-sequence); holding the tail until its newline
            # arrives keeps characters and lines intact across sweeps.
            nl = chunk.rfind(b"\n")
            if nl < 0:
                if len(chunk) < 65536:
                    continue  # wait for the newline
                nl = len(chunk) - 1  # pathological no-newline flood: flush
            chunk = chunk[:nl + 1]
            self._offsets[path] = offset + len(chunk)
            stream = self.out if m.group("stream") == "out" else self.err
            prefix = f"({m.group('hex')[:8]}) "
            for line in chunk.decode(errors="replace").splitlines():
                if line.strip():
                    print(prefix + line, file=stream)
