"""Actor API: ActorClass / ActorHandle / ActorMethod.

Counterpart of python/ray/actor.py: @remote on a class yields an ActorClass
whose .remote() registers the actor with the control plane and returns a
handle; handle.method.remote() submits ordered tasks directly to the actor's
worker process (peer-to-peer, reference direct_actor_task_submitter.cc).
Handles are picklable and can be passed into tasks/other actors.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.core.runtime import func_content_id, get_runtime


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        from ray_tpu.core.streaming import STREAMING
        from ray_tpu.core.task_spec import KwargsMarker

        call_args = list(args)
        if kwargs:
            call_args.append(KwargsMarker(kwargs))
        refs = get_runtime().submit_actor_task(
            self._handle._actor_hex, self._method_name, call_args,
            num_returns=self._num_returns)
        if self._num_returns == STREAMING:
            return refs  # an ObjectRefGenerator
        if self._num_returns == 1:
            return refs[0]
        return refs

    def options(self, num_returns=1):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def bind(self, *args, **kwargs):
        """Author a DAG node for this method call (reference
        class_node.py; see ray_tpu.dag)."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_hex: str, class_name: str = ""):
        self._actor_hex = actor_hex
        self._class_name = class_name
        get_runtime().subscribe_actor(actor_hex)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_hex[:8]})"

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_hex, self._class_name))

    @property
    def actor_id(self):
        from ray_tpu.core.ids import ActorID

        return ActorID.from_hex(self._actor_hex)

    def _wait_until_ready(self, timeout: Optional[float] = None):
        st = get_runtime().wait_actor_alive(self._actor_hex, timeout)
        if st["state"] == "DEAD":
            from ray_tpu.core.exceptions import ActorDiedError

            raise ActorDiedError(self._actor_hex, st.get("reason", ""))
        return self


def _rebuild_handle(actor_hex: str, class_name: str):
    return ActorHandle(actor_hex, class_name)


def method(*, concurrency_group: Optional[str] = None):
    """Method decorator (reference ray.method): annotate an actor method
    with its concurrency group.

        @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
        class A:
            @ray_tpu.method(concurrency_group="io")
            def fetch(self): ...

    (Per-method num_returns rides ActorMethod.options(num_returns=...)
    at the call site instead.)"""

    def decorator(fn):
        if concurrency_group is not None:
            fn.__concurrency_group__ = concurrency_group
        return fn

    return decorator


class ActorClass:
    def __init__(self, cls, *, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 max_restarts: int = 0,
                 max_task_retries: int = 0,
                 max_concurrency: int = 1,
                 concurrency_groups: Optional[Dict[str, int]] = None,
                 name: str = "",
                 namespace: str = "",
                 lifetime: str = "",
                 runtime_env: Optional[dict] = None,
                 scheduling_strategy=None):
        self._cls = cls
        self._num_cpus = 1.0 if num_cpus is None else num_cpus
        self._num_tpus = num_tpus or 0.0
        self._resources = dict(resources or {})
        self._max_restarts = max_restarts
        self._max_task_retries = max_task_retries
        # max_concurrency is the SYNC-method thread count. Async methods
        # always overlap: the worker schedules coroutines on the actor's
        # event loop without parking a thread per call (worker.py
        # _execute_async_actor_task), so async actors need no bump here.
        self._max_concurrency = max_concurrency
        # Named concurrency groups: each group gets its own executor
        # pool in the hosting worker (reference
        # concurrency_group_manager.cc); methods pick a group via
        # @ray_tpu.method(concurrency_group=...).
        self._concurrency_groups = dict(concurrency_groups or {})
        self._name = name
        self._namespace = namespace
        self._runtime_env = runtime_env
        self._scheduling_strategy = scheduling_strategy
        self._blob: Optional[bytes] = None
        self._class_id: Optional[str] = None

    def _resource_demand(self) -> Dict[str, float]:
        demand = dict(self._resources)
        if self._num_cpus:
            demand["CPU"] = self._num_cpus
        if self._num_tpus:
            demand["TPU"] = self._num_tpus
        return demand

    def _ensure_blob(self):
        if self._blob is None:
            self._blob = cloudpickle.dumps(self._cls)
            self._class_id = (
                f"{self._cls.__name__}:{func_content_id(self._blob)}")
        return self._class_id, self._blob

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote(...)")

    def remote(self, *args, **kwargs):
        from ray_tpu.core.task_spec import KwargsMarker

        class_id, blob = self._ensure_blob()
        call_args = list(args)
        if kwargs:
            call_args.append(KwargsMarker(kwargs))
        actor_id = get_runtime().create_actor(
            class_id, blob, call_args,
            resources=self._resource_demand(),
            max_restarts=self._max_restarts,
            max_task_retries=self._max_task_retries,
            name=self._name,
            namespace=self._namespace,
            max_concurrency=self._max_concurrency,
            concurrency_groups=self._concurrency_groups,
            runtime_env=self._runtime_env,
            scheduling_strategy=self._scheduling_strategy,
        )
        return ActorHandle(actor_id.hex(), self._cls.__name__)

    def options(self, **overrides):
        opts = {
            "num_cpus": self._num_cpus,
            "num_tpus": self._num_tpus,
            "resources": self._resources,
            "max_restarts": self._max_restarts,
            "max_task_retries": self._max_task_retries,
            "max_concurrency": self._max_concurrency,
            "concurrency_groups": self._concurrency_groups,
            "name": self._name,
            "namespace": self._namespace,
            "runtime_env": self._runtime_env,
            "scheduling_strategy": self._scheduling_strategy,
        }
        opts.update(overrides)
        opts.pop("lifetime", None)
        clone = ActorClass(self._cls, **opts)
        clone._blob = self._blob
        clone._class_id = self._class_id
        return clone
