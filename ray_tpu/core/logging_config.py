"""Structured logging configuration (counterpart of ray.LoggingConfig,
python/ray/_private/ray_logging/__init__.py + logging_config.py).

``ray_tpu.init(logging_config=LoggingConfig(encoding="JSON"))`` configures
the driver process AND every worker the session spawns: the config rides
the environment (workers inherit it at spawn — exec or zygote fork alike)
and ``apply_from_env`` runs in worker startup before user code.

JSON encoding emits one object per record with timestamp/level/logger/
message plus the executing task/actor context (the reference's structured
logs carry job/worker/task ids the same way), so log aggregators can join
worker logs against the state API without parsing freeform text.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Optional, Sequence

_ENV_KEY = "RAY_TPU_LOGGING_CONFIG"
_VALID_ENCODINGS = ("TEXT", "JSON")


@dataclasses.dataclass
class LoggingConfig:
    encoding: str = "TEXT"
    log_level: str = "INFO"
    # Extra standard LogRecord attributes to include in JSON records
    # (e.g. "filename", "lineno", "threadName").
    additional_log_standard_attrs: Sequence[str] = ()

    def __post_init__(self):
        enc = str(self.encoding).upper()
        if enc not in _VALID_ENCODINGS:
            raise ValueError(
                f"encoding must be one of {_VALID_ENCODINGS}, got "
                f"{self.encoding!r}")
        self.encoding = enc
        self.log_level = str(self.log_level).upper()
        # Validate NOW: a bad level must fail at construction in the
        # driver, not crash every worker at startup via apply_from_env.
        if logging.getLevelName(self.log_level) == \
                f"Level {self.log_level}":
            raise ValueError(f"unknown log_level {self.log_level!r}")

    def to_env(self) -> str:
        return json.dumps({
            "encoding": self.encoding,
            "log_level": self.log_level,
            "additional_log_standard_attrs":
                list(self.additional_log_standard_attrs),
        })

    @classmethod
    def from_env(cls, raw: str) -> "LoggingConfig":
        d = json.loads(raw)
        return cls(encoding=d.get("encoding", "TEXT"),
                   log_level=d.get("log_level", "INFO"),
                   additional_log_standard_attrs=tuple(
                       d.get("additional_log_standard_attrs", ())))


class JsonFormatter(logging.Formatter):
    """One JSON object per record, with executing-task context."""

    def __init__(self, extra_attrs: Sequence[str] = ()):
        super().__init__()
        self.extra_attrs = tuple(extra_attrs)
        # Fixed for the process lifetime; resolve once, not per record.
        self._static_ctx = {
            k: v for k, v in (
                ("worker_id", os.environ.get("RAY_TPU_WORKER_ID")),
                ("node_id", os.environ.get("RAY_TPU_NODE_ID")),
            ) if v}

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "asctime": time.strftime(
                "%Y-%m-%d %H:%M:%S", time.localtime(record.created)),
            "levelname": record.levelname,
            "name": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exc_text"] = self.formatException(record.exc_info)
        for attr in self.extra_attrs:
            out[attr] = getattr(record, attr, None)
        out.update(self._static_ctx)
        out.update(_task_context_fields())
        return json.dumps(out)


def _task_context_fields() -> dict:
    """Per-record dynamic context: the executing task/actor ids."""
    try:
        from ray_tpu.core.runtime_context import get_runtime_context

        ctx = get_runtime_context()
        fields = {}
        tid = ctx.get_task_id()
        if tid:
            fields["task_id"] = tid
        aid = ctx.get_actor_id()
        if aid:
            fields["actor_id"] = aid
        return fields
    except Exception:
        return {}


def apply(config: LoggingConfig) -> None:
    """Configure the root logger of THIS process per ``config``."""
    root = logging.getLogger()
    root.setLevel(config.log_level)
    if not root.handlers:
        root.addHandler(logging.StreamHandler())
    for h in root.handlers:
        if config.encoding == "JSON":
            h.setFormatter(JsonFormatter(
                config.additional_log_standard_attrs))
        else:
            h.setFormatter(logging.Formatter(
                "%(asctime)s\t%(levelname)s %(name)s -- %(message)s"))


def export_to_env(config: Optional[LoggingConfig]) -> None:
    """Driver side: publish the config so spawned workers inherit it."""
    if config is None:
        os.environ.pop(_ENV_KEY, None)
    else:
        os.environ[_ENV_KEY] = config.to_env()


def apply_from_env() -> Optional[LoggingConfig]:
    """Worker side: apply the session's logging config, if any.  A
    malformed value must never kill the worker — logging is advisory."""
    raw = os.environ.get(_ENV_KEY)
    if not raw:
        return None
    try:
        config = LoggingConfig.from_env(raw)
        apply(config)
    except Exception:
        return None
    return config
