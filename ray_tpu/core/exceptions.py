"""Exception hierarchy (counterpart of python/ray/exceptions.py)."""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Stored in place of the task's return object; re-raised (wrapped) on get(),
    mirroring the reference's RayTaskError semantics
    (python/ray/exceptions.py).
    """

    def __init__(self, function_name: str, cause: BaseException | None = None, tb: str = ""):
        self.function_name = function_name
        self.cause = cause
        self.traceback_str = tb or (
            "".join(traceback.format_exception(cause)) if cause is not None else ""
        )
        super().__init__(
            f"Task {function_name!r} failed:\n{self.traceback_str}"
        )


class ActorError(RayTpuError):
    """Actor died before/while executing a submitted method."""


class ActorDiedError(ActorError):
    def __init__(self, actor_id, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} died: {reason}")


class ObjectLostError(RayTpuError):
    """Object value is unrecoverable (all copies lost, lineage exhausted)."""


class ObjectFreedError(RayTpuError):
    """Object was explicitly freed."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """get() timed out."""


class WorkerCrashedError(RayTpuError):
    """Worker process died while executing a task."""


class TaskCancelledError(RayTpuError):
    """Task was cancelled before or during execution."""


class TaskUnschedulableError(RayTpuError):
    """Task can never be scheduled (e.g. its placement group was removed)."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a worker."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor max_pending_calls exceeded."""
