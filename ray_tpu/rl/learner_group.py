"""LearnerGroup: one local learner or N learner actors with host-level DP.

Counterpart of the reference's rllib/core/learner/learner_group.py (:82),
which reuses Ray Train's BackendExecutor + TorchConfig to set up DDP across
learner actors (:135–165).  Here the two data-parallel tiers are explicit:

  - intra-host (chips): each learner jits its update over a device mesh;
    GSPMD psum handles the gradient reduction on ICI (learner.py).
  - inter-learner (hosts): the group shards the batch across learner
    actors, gathers grads through the object store, averages, and applies
    — the reference's split gradient API (learner.py:446–568) made the
    cross-host reduction, since there is no NCCL process group to hide it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

import ray_tpu


def _shard_batch(batch: Dict[str, np.ndarray], n: int
                 ) -> List[Dict[str, np.ndarray]]:
    """Split on the leading axis into n near-equal shards."""
    out: List[Dict[str, np.ndarray]] = []
    size = next(iter(batch.values())).shape[0]
    bounds = np.linspace(0, size, n + 1).astype(int)
    for i in range(n):
        lo, hi = bounds[i], bounds[i + 1]
        out.append({k: v[lo:hi] for k, v in batch.items()})
    return out


class LearnerGroup:
    def __init__(self, learner_cls, learner_kwargs: Dict[str, Any], *,
                 num_learners: int = 0):
        self.num_learners = num_learners
        self.local_learner = None
        self.remote_learners: List[Any] = []
        if num_learners == 0:
            self.local_learner = learner_cls(**learner_kwargs)
        else:
            actor_cls = ray_tpu.remote(learner_cls)
            self.remote_learners = [
                actor_cls.options(name=f"learner_{i}_{id(self)}").remote(
                    **learner_kwargs)
                for i in range(num_learners)]
            # Rank-0 weights are the source of truth; align the others.
            w = ray_tpu.get(self.remote_learners[0].get_weights.remote())
            ray_tpu.get([l.set_weights.remote(w)
                         for l in self.remote_learners[1:]])

    def update_from_batch(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, float]:
        if self.local_learner is not None:
            return self.local_learner.update_from_batch(batch)
        shards = _shard_batch(batch, len(self.remote_learners))
        grad_refs = [l.compute_gradients.remote(s)
                     for l, s in zip(self.remote_learners, shards)]
        results = ray_tpu.get(grad_refs)
        grads = [g for g, _ in results]
        # Weight by each shard's effective sample count (mask sum when the
        # loss is mask-normalized, else rows) so the average equals the
        # full-batch gradient even with uneven shards / padded rows.
        w = np.asarray([
            float(s["mask"].sum()) if "mask" in s
            else float(next(iter(s.values())).shape[0])
            for s in shards])
        w = w / np.maximum(w.sum(), 1e-8)
        avg = jax.tree.map(
            lambda *xs: np.tensordot(w, np.stack(xs), axes=1).astype(
                np.asarray(xs[0]).dtype),
            *grads)
        ray_tpu.get([l.apply_gradients.remote(avg)
                     for l in self.remote_learners])
        auxes = [aux for _, aux in results]
        return {k: float(np.mean([a[k] for a in auxes]))
                for k in auxes[0]}

    def foreach_learner(self, method: str, *args, **kwargs) -> List[Any]:
        """Call a learner method everywhere (reference: LearnerGroup's
        additional_update / foreach_learner fan-out). Used for e.g. DQN
        target-network syncs."""
        if self.local_learner is not None:
            return [getattr(self.local_learner, method)(*args, **kwargs)]
        return ray_tpu.get([
            getattr(l, method).remote(*args, **kwargs)
            for l in self.remote_learners])

    def get_weights(self):
        if self.local_learner is not None:
            return self.local_learner.get_weights()
        return ray_tpu.get(self.remote_learners[0].get_weights.remote())

    def set_weights(self, params) -> None:
        if self.local_learner is not None:
            self.local_learner.set_weights(params)
        else:
            ray_tpu.get([l.set_weights.remote(params)
                         for l in self.remote_learners])

    def get_state(self) -> Dict[str, Any]:
        if self.local_learner is not None:
            return self.local_learner.get_state()
        return ray_tpu.get(self.remote_learners[0].get_state.remote())

    def set_state(self, state: Dict[str, Any]) -> None:
        if self.local_learner is not None:
            self.local_learner.set_state(state)
        else:
            ray_tpu.get([l.set_state.remote(state)
                         for l in self.remote_learners])

    def stop(self) -> None:
        for l in self.remote_learners:
            try:
                ray_tpu.kill(l)
            except Exception:
                pass
        self.remote_learners = []
