"""Sequence batching shared by recurrent learners (PPO, IMPALA/APPO).

Counterpart of the reference's rllib/policy/rnn_sequencing.py (max_seq_len
padding) reframed for the new-stack episode rows this stack trains on:
each GAE/V-trace row (one episode fragment) is cut into `max_seq_len`
segments with zero LSTM state at segment starts (truncated BPTT); padded
steps carry mask 0 and `is_first` marks the in-scan state resets.  The
jitted update's shape is [mb, T], so a varying segment count costs no
recompile — only the minibatch slice shape is compiled.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def segment_rows(rows: List[Dict[str, np.ndarray]], T: int
                 ) -> List[Dict[str, np.ndarray]]:
    """Cut per-episode row dicts into [T]-step segments with mask and
    is_first columns appended."""
    segs: List[Dict[str, np.ndarray]] = []
    for row in rows:
        L = len(row["obs"])
        for s in range(0, L, T):
            seg = {k: v[s:s + T] for k, v in row.items()}
            n = len(seg["obs"])
            if n < T:
                seg = {k: np.concatenate(
                    [v, np.zeros((T - n,) + v.shape[1:], v.dtype)])
                    for k, v in seg.items()}
            mask = np.zeros(T, np.float32)
            mask[:n] = 1.0
            isf = np.zeros(T, np.float32)
            isf[0] = 1.0  # zero state at every segment start
            seg["mask"], seg["is_first"] = mask, isf
            segs.append(seg)
    return segs


def stack_segments(segs: List[Dict[str, np.ndarray]], target: int
                   ) -> Dict[str, np.ndarray]:
    """Stack segments into [target, T, ...] arrays, padding with
    all-zero segments (mask 0, is_first kept so scan resets stay
    defined).  target must be >= len(segs)."""
    assert segs and target >= len(segs)
    if len(segs) < target:
        zero = {k: np.zeros_like(v) for k, v in segs[0].items()}
        zero["is_first"] = segs[0]["is_first"]
        segs = segs + [zero] * (target - len(segs))
    return {k: np.stack([s[k] for s in segs]) for k in segs[0]}


def forward_episodes_seq(spec, params, episodes, *,
                         reset_every: int = 0
                         ) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """(dist_inputs [N, Lmax, ·], values [N, Lmax], lens) for whole
    episode obs sequences through spec.forward_seq — the recurrent
    replacement for the flat concat+forward the on-policy target/value
    computations (GAE bootstrap, V-trace) otherwise use.  Both axes pad
    to powers of two so the scan compiles a bounded number of shapes.

    reset_every > 0 zeroes the LSTM state at every that-many-step
    boundary (per episode), matching the learner's truncated-BPTT
    segment view — V-trace targets must be computed from the SAME state
    trajectory the loss will recompute, or rho/vf regress against a
    different value view.  0 = continuous state across the fragment
    (GAE bootstrap, which extends the rollout's own value stream)."""
    import jax.numpy as jnp

    lens = [len(e.obs) for e in episodes]
    Lmax = 1 << (max(lens) - 1).bit_length()
    N = 1 << (len(episodes) - 1).bit_length()
    obs_dim = int(np.prod(np.asarray(episodes[0].obs[0]).shape))
    obs_pad = np.zeros((N, Lmax, obs_dim), np.float32)
    isf = np.zeros((N, Lmax), np.float32)
    isf[:, 0] = 1.0
    if reset_every > 0:
        isf[:, ::reset_every] = 1.0
    for i, e in enumerate(episodes):
        obs_pad[i, :lens[i]] = np.asarray(e.obs).reshape(lens[i], -1)
    di, vals = spec.forward_seq(params, jnp.asarray(obs_pad),
                                jnp.asarray(isf))
    return np.asarray(di), np.asarray(vals), lens
