"""Sequence batching shared by recurrent learners (PPO, IMPALA/APPO).

Counterpart of the reference's rllib/policy/rnn_sequencing.py (max_seq_len
padding) reframed for the new-stack episode rows this stack trains on:
each GAE/V-trace row (one episode fragment) is cut into `max_seq_len`
segments with zero LSTM state at segment starts (truncated BPTT); padded
steps carry mask 0 and `is_first` marks the in-scan state resets.  The
jitted update's shape is [mb, T], so a varying segment count costs no
recompile — only the minibatch slice shape is compiled.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def normalize_advantages(batch: Dict[str, np.ndarray]) -> None:
    """In-place masked advantage standardization (flat [N] or [N, T])."""
    valid = batch["mask"] > 0
    mean = batch["advantages"][valid].mean()
    std = batch["advantages"][valid].std() + 1e-8
    batch["advantages"] = np.where(
        valid, (batch["advantages"] - mean) / std, 0.0
    ).astype(np.float32)


def segment_rows(rows: List[Dict[str, np.ndarray]], T: int
                 ) -> List[Dict[str, np.ndarray]]:
    """Cut per-episode row dicts into [T]-step segments with mask and
    is_first columns appended.

    Rows carrying per-step entering states ("state_h"/"state_c", the
    env runner's recording) turn into "h0"/"c0" seed columns — each
    segment starts from the state the behavior policy actually carried
    there (the reference's state_in), so recomputed logp/values match
    the rollout under unchanged params.  Without recorded states,
    segments start from zeros (is_first reset at t=0)."""
    segs: List[Dict[str, np.ndarray]] = []
    for row in rows:
        seeded = "state_h" in row
        L = len(row["obs"])
        for s in range(0, L, T):
            seg = {k: v[s:s + T] for k, v in row.items()
                   if k not in ("state_h", "state_c")}
            n = len(seg["obs"])
            if n < T:
                seg = {k: np.concatenate(
                    [v, np.zeros((T - n,) + v.shape[1:], v.dtype)])
                    for k, v in seg.items()}
            mask = np.zeros(T, np.float32)
            mask[:n] = 1.0
            isf = np.zeros(T, np.float32)
            if seeded:
                seg["h0"] = np.asarray(row["state_h"][s], np.float32)
                seg["c0"] = np.asarray(row["state_c"][s], np.float32)
            else:
                isf[0] = 1.0  # zero state at every segment start
            seg["mask"], seg["is_first"] = mask, isf
            segs.append(seg)
    return segs


def stack_segments(segs: List[Dict[str, np.ndarray]], target: int
                   ) -> Dict[str, np.ndarray]:
    """Stack segments into [target, T, ...] arrays, padding with
    all-zero segments (mask 0, is_first kept so scan resets stay
    defined).  target must be >= len(segs)."""
    assert segs and target >= len(segs)
    if len(segs) < target:
        zero = {k: np.zeros_like(v) for k, v in segs[0].items()}
        zero["is_first"] = segs[0]["is_first"]
        segs = segs + [zero] * (target - len(segs))
    return {k: np.stack([s[k] for s in segs]) for k in segs[0]}


def episode_states(ep) -> Tuple[np.ndarray, np.ndarray]:
    """Entering states for every obs position 0..T of a finalized
    episode: the per-step recording plus the final_state the runner
    attached for the last obs.  [T+1, cell] each."""
    h = np.asarray(ep.extra["state_h"], np.float32)
    c = np.asarray(ep.extra["state_c"], np.float32)
    fin = ep.final_state
    fh = (np.asarray(fin["h"], np.float32) if fin is not None
          else np.zeros_like(h[0]))
    fc = (np.asarray(fin["c"], np.float32) if fin is not None
          else np.zeros_like(c[0]))
    return (np.concatenate([h, fh[None]]),
            np.concatenate([c, fc[None]]))


def forward_rows_seeded(spec, params, obs_rows: List[np.ndarray],
                        h_rows: List[np.ndarray],
                        c_rows: List[np.ndarray], T: int
                        ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """(dist_inputs [n_i, ·], values [n_i]) per row, computed by cutting
    each row into [T]-step segments seeded with its RECORDED entering
    states and running ONE forward_seq scan over the stacked segments —
    the recurrent replacement for the flat concat+forward the on-policy
    target computations (V-trace, GAE bootstrap) otherwise use.  The
    segment count pads to a power of two (bounded compiled shapes)."""
    import jax.numpy as jnp

    obs_dim = obs_rows[0].shape[-1]
    cell = h_rows[0].shape[-1]
    chunks: List[Tuple[int, int, int]] = []  # (row, start, n)
    for i, o in enumerate(obs_rows):
        for s in range(0, len(o), T):
            chunks.append((i, s, min(T, len(o) - s)))
    S = 1 << (len(chunks) - 1).bit_length()
    obs = np.zeros((S, T, obs_dim), np.float32)
    h0 = np.zeros((S, cell), np.float32)
    c0 = np.zeros((S, cell), np.float32)
    for j, (i, s, n) in enumerate(chunks):
        obs[j, :n] = obs_rows[i][s:s + n]
        h0[j] = h_rows[i][s]
        c0[j] = c_rows[i][s]
    di, vals = spec.forward_seq(
        params, jnp.asarray(obs), jnp.zeros((S, T), jnp.float32),
        jnp.asarray(h0), jnp.asarray(c0))
    di, vals = np.asarray(di), np.asarray(vals)
    out: List[Tuple[np.ndarray, np.ndarray]] = [
        (np.zeros((len(o), di.shape[-1]), np.float32),
         np.zeros(len(o), np.float32)) for o in obs_rows]
    for j, (i, s, n) in enumerate(chunks):
        out[i][0][s:s + n] = di[j, :n]
        out[i][1][s:s + n] = vals[j, :n]
    return out
