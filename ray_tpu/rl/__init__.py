"""ray_tpu.rl: the RLlib-equivalent — env runners, JAX learners, algorithms.

Counterpart of the reference's rllib/ new API stack: AlgorithmConfig →
Algorithm (a Tune Trainable), EnvRunnerGroup of rollout actors, LearnerGroup
of JAX learners whose update is one jitted step (SURVEY.md §2.3 L5, §3.5).
"""

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.connectors import (
    ClipContinuousActions,
    ConnectorPipelineV2,
    ConnectorV2,
    EpsilonGreedy,
    FlattenObservations,
    FrameStackingConnector,
    MeanStdObservationFilter,
)
from ray_tpu.rl.env_runner import SingleAgentEnvRunner
from ray_tpu.rl.env_runner_group import EnvRunnerGroup
from ray_tpu.rl.episode import SingleAgentEpisode, episodes_to_batch
from ray_tpu.rl.learner import JaxLearner
from ray_tpu.rl.learner_group import LearnerGroup
from ray_tpu.rl.catalog import MODEL_DEFAULTS, Catalog
from ray_tpu.rl.module import (ConvQNetworkSpec, ConvRLModuleSpec,
                               QNetworkSpec, RecurrentRLModuleSpec,
                               RLModuleSpec, SACModuleSpec)
from ray_tpu.rl.offline import (
    dataset_to_episodes,
    episodes_to_dataset,
    read_offline_episodes,
    write_offline_dataset,
)
from ray_tpu.rl.multi_agent import (
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rl.replay_buffer import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
    SequenceReplayBuffer,
)

__all__ = [
    "ConnectorV2",
    "ConnectorPipelineV2",
    "FrameStackingConnector",
    "MeanStdObservationFilter",
    "FlattenObservations",
    "EpsilonGreedy",
    "ClipContinuousActions",
    "PrioritizedReplayBuffer",
    "SequenceReplayBuffer",
    "QNetworkSpec",
    "ReplayBuffer",
    "SACModuleSpec",
    "Algorithm",
    "AlgorithmConfig",
    "SingleAgentEnvRunner",
    "EnvRunnerGroup",
    "SingleAgentEpisode",
    "episodes_to_batch",
    "JaxLearner",
    "LearnerGroup",
    "Catalog",
    "MODEL_DEFAULTS",
    "ConvQNetworkSpec",
    "ConvRLModuleSpec",
    "RecurrentRLModuleSpec",
    "RLModuleSpec",
    "dataset_to_episodes",
    "episodes_to_dataset",
    "read_offline_episodes",
    "write_offline_dataset",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
]

# Feature-usage tag (util/usage_stats.py; local-only, no egress).
from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("rl")
del _rlu
